"""Warm leader-failover re-seed equivalence (ISSUE 13).

A post-election leader re-seeds its term structures from the replicated
store — node-tensor usage (TensorIndex.resync_usage), the ChainArbiter's
committed chain basis, and the QoS first-enqueue ages the broker restores
from the FSM timetable — instead of starting cold. These fixed-seed gates
assert the re-seeded leader is indistinguishable from a leader that never
failed: same usage rows, same chain basis, same queue ages and tier
dequeue ordering, and a recovered storm commits the same placements.

The "failed over" server is built by round-tripping the never-failed
server's FSM through the CHUNKED snapshot stream (the streaming-snapshot
wire path) and establishing leadership on the restored state — exactly
what a new leader does after an election plus InstallSnapshot.
"""

import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.qos import QoSConfig
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs.structs import (
    EvalStatusCancelled,
    EvalStatusComplete,
    EvalStatusFailed,
)

from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry

TERMINAL = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)


def svc_job(priority=50, count=2, cpu=60):
    job = mock.job()
    job.Priority = priority
    tg = job.TaskGroups[0]
    tg.Count = count
    task = tg.Tasks[0]
    task.Resources.CPU = cpu
    task.Resources.MemoryMB = 32
    task.Resources.Networks = []
    task.Services = []
    job.init_fields()
    return job


def failover_from(src: Server, cfg: ServerConfig,
                  chunks=None) -> Server:
    """Build the post-election leader: a fresh Server whose FSM is
    restored from `src`'s CHUNKED snapshot stream (or a pre-captured
    chunk list), with the dev-raft index advanced past the restored
    watermark the way a real raft restore sets _last_applied."""
    out = Server(cfg)
    out.fsm.restore_chunks(iter(chunks) if chunks is not None
                           else src.fsm.snapshot_chunks(chunk_items=5))
    out.raft._index = max(out.raft._index, out.fsm.state.latest_index())
    return out


def usage_by_node(srv: Server):
    nt = srv.tindex.nt
    with nt._lock:
        return {nid: nt.usage[row].copy()
                for nid, row in nt.row_of.items()}


def all_terminal(srv: Server, eval_ids):
    return all((e := srv.state.eval_by_id(eid)) is not None
               and e.Status in TERMINAL for eid in eval_ids)


class TestNodeTensorReseed:
    def test_usage_and_chain_basis_match_never_failed_leader(self):
        """After a storm commits, a failed-over leader's node-tensor
        usage must equal the never-failed leader's row for row — even
        when the follower tensor drifted before the election — and both
        arbiters' next window must chain on that same committed basis."""
        cfg = dict(num_schedulers=1, scheduler_window=8,
                   min_heartbeat_ttl=3600.0, heartbeat_grace=3600.0)
        a = Server(ServerConfig(**cfg))
        a.establish_leadership()
        b = None
        try:
            for _ in range(6):
                a.node_register(mock.node())
            eval_ids = [a.job_register(svc_job())[0] for _ in range(4)]
            assert wait_for(lambda: all_terminal(a, eval_ids), timeout=30,
                            msg="storm on the never-failed leader")
            want = usage_by_node(a)
            assert any(v.any() for v in want.values())  # storm landed

            b = failover_from(a, ServerConfig(**cfg))
            # Simulate follower drift across the election window: one
            # row's usage is wrong when the new term begins.
            nt_b = b.tindex.nt
            with nt_b._lock:
                nt_b.usage[0] += 7.0
            b.establish_leadership()   # warm re-seed corrects it

            got = usage_by_node(b)
            assert set(got) == set(want)
            for nid in want:
                assert np.allclose(got[nid], want[nid], atol=1e-9), nid
            # Idempotent: a second resync finds zero drifted rows.
            assert b.tindex.resync_usage(b.state) == 0

            # Chain basis: both leaders' next window rebases onto the
            # SAME committed usage (nothing in flight on either side).
            for srv in (a, b):
                arb = srv.workers[0]._arbiter
                lease = arb.acquire(holder="gate")
                try:
                    assert lease.chain is None  # chains on committed rows
                finally:
                    arb.abort(lease)
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()


class TestQoSAgeReseed:
    def test_queue_ages_and_tier_order_match(self):
        """Queued evals ride the election warm: the restored broker seeds
        each eval's first-enqueue age from the replicated timetable, so
        (a) no queued eval resets to age zero, (b) the seed errs OLDER
        (never loses its place behind fresh arrivals), and (c) the tier
        dequeue order matches the never-failed leader's exactly."""
        cfg = dict(num_schedulers=0, qos=QoSConfig(enabled=True),
                   min_heartbeat_ttl=3600.0, heartbeat_grace=3600.0)
        a = Server(ServerConfig(**cfg))
        # Test-speed witness granularity (default 300s would collapse
        # every index onto one wall anchor; ages would still err older,
        # but the per-eval ordering we assert needs distinct anchors).
        a.fsm.timetable.granularity = 0.01
        a.establish_leadership()
        b = None
        try:
            for _ in range(3):
                a.node_register(mock.node())
            eval_ids = []
            for prio in (80, 20, 50, 80, 20, 50):
                eval_ids.append(a.job_register(svc_job(priority=prio))[0])
                time.sleep(0.06)  # distinct timetable witnesses
            chunks = list(a.fsm.snapshot_chunks(chunk_items=4))
            ages_a = {eid: a.eval_broker.queue_age(eid)
                      for eid in eval_ids}
            assert all(ts is not None for ts in ages_a.values())

            b = failover_from(a, ServerConfig(**cfg), chunks=chunks)
            b.establish_leadership()

            for eid in eval_ids:
                ts_b = b.eval_broker.queue_age(eid)
                assert ts_b is not None, "eval lost its age in failover"
                # Same monotonic clock domain (one process): the seeded
                # first-enqueue time must not be NEWER than the true one
                # (plus witness slack) — erring older is the contract.
                assert ts_b <= ages_a[eid] + 0.25, eid

            def drain(srv):
                order = []
                while True:
                    ev, _tok = srv.eval_broker.dequeue(["service"],
                                                       timeout=0.2)
                    if ev is None:
                        return order
                    order.append(ev.ID)

            order_a, order_b = drain(a), drain(b)
            assert len(order_a) == len(eval_ids)
            assert order_b == order_a, "tier/age dequeue order diverged"
            # And the order is the QoS one: both high-tier evals first.
            high = {eid for eid, prio in zip(eval_ids,
                                             (80, 20, 50, 80, 20, 50))
                    if prio >= 70}
            assert set(order_a[:2]) == high
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()


class TestQoSBurnSlack:
    def test_witness_slack_keeps_restored_evals_out_of_burn(self):
        """The err-older age seed must NOT count as SLO burn: on a
        cluster older than the high-tier deadline (coarse default
        timetable granularity -> the seed errs older by the cluster's
        whole age), a restored eval acked promptly records ZERO burn —
        while an eval whose post-restore wait genuinely blows the
        deadline still burns. Without the witness slack, every election
        on a >deadline-age cluster would saturate the burn rings and
        trip admission shedding."""
        cfg = dict(num_schedulers=0, qos=QoSConfig(enabled=True),
                   min_heartbeat_ttl=3600.0, heartbeat_grace=3600.0)
        a = Server(ServerConfig(**cfg))  # default 300s witness granularity
        a.establish_leadership()
        b = None
        try:
            a.node_register(mock.node())
            # Age the cluster past the high-tier deadline (0.25s), THEN
            # create the evals: their CreateIndex maps back to the boot
            # witness, so the restored seed errs older by > deadline.
            time.sleep(0.5)
            e_fast = a.job_register(svc_job(priority=80))[0]
            e_slow = a.job_register(svc_job(priority=80))[0]
            chunks = list(a.fsm.snapshot_chunks())

            b = failover_from(a, ServerConfig(**cfg), chunks=chunks)
            b.establish_leadership()
            # Ordering still errs older: the seeded first-enqueue is in
            # the past.
            assert b.eval_broker.queue_age(e_fast) < time.monotonic()

            def ack_one(want_eval):
                ev, tok = b.eval_broker.dequeue(["service"], timeout=5)
                assert ev is not None and ev.ID == want_eval
                b.eval_broker.ack(ev.ID, tok)

            ack_one(e_fast)  # prompt ack: wait-since-restore ~ 0
            burn = b.eval_broker.slo_burn()
            assert burn[0] == 0.0, \
                f"restored eval's witness slack counted as burn: {burn}"
            # A REAL post-restore wait past the deadline still burns —
            # the slack is a witness-error correction, not amnesty.
            time.sleep(0.4)
            ack_one(e_slow)
            assert b.eval_broker.slo_burn()[0] == 0.5  # 1 of 2 burned
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()


class TestRecoveredStormPlacement:
    def test_recovered_storm_places_identically(self, monkeypatch):
        """The full composition: a mixed-priority storm queued at the
        moment of failover places EXACTLY like the never-failed leader —
        same (job, instance-name) -> node assignments, no lost evals, no
        duplicate allocs — because usage, chain basis, and queue order
        all re-seeded warm.

        The stack's tie-break noise is deliberately unseeded in
        production (load spreading); zero it here so this gate compares
        the warm re-seed, not two dice rolls over identical nodes."""
        import nomad_tpu.scheduler.stack as stack_mod

        monkeypatch.setattr(
            stack_mod, "make_noise_vec",
            lambda n_rows, rng: np.zeros(n_rows, dtype=np.float32))
        cfg = dict(num_schedulers=1, scheduler_window=8,
                   qos=QoSConfig(enabled=True),
                   min_heartbeat_ttl=3600.0, heartbeat_grace=3600.0)
        a = Server(ServerConfig(**cfg))
        b = None
        try:
            # Build the pre-failover world WITHOUT leadership: evals are
            # replicated state, none dequeued yet (a storm arriving just
            # as the old leader died).
            for _ in range(5):
                a.node_register(mock.node())
            jobs = [svc_job(priority=p) for p in (80, 20, 50, 80, 20)]
            eval_ids = [a.job_register(job)[0] for job in jobs]
            chunks = list(a.fsm.snapshot_chunks(chunk_items=7))

            # Leader that never failed drains the storm...
            a.establish_leadership()
            assert wait_for(lambda: all_terminal(a, eval_ids), timeout=30,
                            msg="never-failed leader drains the storm")

            # ...and the failed-over leader drains the SAME storm from
            # the restored snapshot.
            b = failover_from(a, ServerConfig(**cfg), chunks=chunks)
            b.establish_leadership()
            assert wait_for(lambda: all_terminal(b, eval_ids), timeout=30,
                            msg="failed-over leader drains the storm")

            def placements(srv):
                out = {}
                ids = set()
                for job in jobs:
                    for al in srv.state.allocs_by_job(job.ID):
                        if al.terminal_status():
                            continue
                        assert al.ID not in ids  # no duplicate allocs
                        ids.add(al.ID)
                        out[(al.JobID, al.Name)] = al.NodeID
                return out

            pa, pb = placements(a), placements(b)
            assert len(pa) == sum(j.TaskGroups[0].Count for j in jobs)
            # Node IDs are shared via the snapshot, so the assignment
            # maps must be EQUAL, not just same-shaped.
            assert pb == pa
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()
