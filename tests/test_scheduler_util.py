"""Scheduler util parity grid (reference: scheduler/util_test.go — the
893-line case grid: materialize, diff, tainted nodes, retry, in-place
updates, evict-and-place limits, set_status variants, constraints,
desired updates). Ported case for case against our scheduler/util.py.
"""

import logging
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.scheduler import SetStatusError
from nomad_tpu.scheduler.stack import GenericStack
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.scheduler.util import (
    AllocTuple,
    DiffResult,
    attempt_inplace_updates,
    desired_updates,
    diff_allocs,
    diff_system_allocs,
    evict_and_place,
    materialize_task_groups,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    task_group_constraints,
    tasks_updated,
)
from nomad_tpu.structs import (
    Allocation,
    NetworkResource,
    PlanResult,
    Port,
    Resources,
    Service,
    compute_node_class,
)
from nomad_tpu.structs.structs import (
    AllocDesiredStatusRun,
    Job,
    NodeStatusDown,
)

logger = logging.getLogger("test.util")


def _copy_job(job):
    return job.copy()


class TestMaterialize:
    def test_count_expansion(self):
        """(reference: TestMaterializeTaskGroups)"""
        job = mock.job()
        index = materialize_task_groups(job)
        assert len(index) == 10
        for i in range(10):
            name = f"{job.Name}.web[{i}]"
            assert index[name] is job.TaskGroups[0]


class TestDiffAllocs:
    def test_update_ignore_stop_migrate_place(self):
        """(reference: TestDiffAllocs)"""
        job = mock.job()
        required = materialize_task_groups(job)
        old_job = _copy_job(job)
        old_job.JobModifyIndex = job.JobModifyIndex - 1
        tainted = {"dead": True, "zip": False}
        names = sorted(required)

        def alloc(name, node, j):
            return Allocation(ID=mock.generate_uuid(), NodeID=node,
                              Name=name, Job=j)

        a_update = alloc(f"{job.Name}.web[0]", "zip", old_job)
        a_ignore = alloc(f"{job.Name}.web[1]", "zip", job)
        a_stop = alloc(f"{job.Name}.web[10]", "zip", old_job)  # not required
        a_migrate = alloc(f"{job.Name}.web[2]", "dead", old_job)
        diff = diff_allocs(job, tainted, required,
                           [a_update, a_ignore, a_stop, a_migrate])
        assert [t.Alloc for t in diff.update] == [a_update]
        assert [t.Alloc for t in diff.ignore] == [a_ignore]
        assert [t.Alloc for t in diff.stop] == [a_stop]
        assert [t.Alloc for t in diff.migrate] == [a_migrate]
        assert len(diff.place) == 7
        assert names  # sanity: required materialized


class TestDiffSystemAllocs:
    def test_per_node_diff(self):
        """(reference: TestDiffSystemAllocs)"""
        job = mock.system_job()
        nodes = [mock.node() for _ in range(3)]
        foo, bar, baz = nodes
        old_job = _copy_job(job)
        old_job.JobModifyIndex = job.JobModifyIndex - 1
        tainted = {"dead": True, baz.ID: False}
        name = next(iter(materialize_task_groups(job)))

        a_update = Allocation(ID="u", NodeID=baz.ID, Name=name, Job=old_job)
        a_ignore = Allocation(ID="i", NodeID=bar.ID, Name=name, Job=job)
        a_stop = Allocation(ID="s", NodeID="dead", Name=name, Job=old_job)
        diff = diff_system_allocs(job, nodes, tainted,
                                  [a_update, a_ignore, a_stop])
        assert [t.Alloc for t in diff.update] == [a_update]
        assert [t.Alloc for t in diff.ignore] == [a_ignore]
        # System jobs don't migrate: the tainted node's alloc stops.
        assert [t.Alloc for t in diff.stop] == [a_stop]
        assert diff.migrate == []
        assert len(diff.place) == 1
        assert diff.place[0].Alloc.NodeID == foo.ID

    def test_duplicate_node_entries_place_once(self):
        """A node list with duplicate entries (double-registered, merged
        from two sources) must not double-place the system task group on
        that node."""
        job = mock.system_job()
        node = mock.node()
        diff = diff_system_allocs(job, [node, node], {}, [])
        assert len(diff.place) == 1
        assert diff.place[0].Alloc.NodeID == node.ID


class TestReadyAndTainted:
    def _store(self):
        h = Harness()
        n1 = mock.node()
        n2 = mock.node()
        n2.Datacenter = "dc2"
        n3 = mock.node()
        n3.Datacenter = "dc2"
        n3.Status = NodeStatusDown
        n4 = mock.node()
        n4.Drain = True
        for n in (n1, n2, n3, n4):
            compute_node_class(n)
            h.upsert("node", n)
        return h.state, (n1, n2, n3, n4)

    def test_ready_nodes_in_dcs(self):
        """(reference: TestReadyNodesInDCs)"""
        state, (n1, n2, n3, n4) = self._store()
        nodes, dc = ready_nodes_in_dcs(state, ["dc1", "dc2"])
        assert len(nodes) == 2
        assert n3.ID not in {n.ID for n in nodes}
        assert n4.ID not in {n.ID for n in nodes}
        assert dc == {"dc1": 1, "dc2": 1}

    def test_tainted_nodes(self):
        """(reference: TestTaintedNodes): down, draining, and VANISHED
        nodes are tainted; healthy ones are present but False."""
        state, (n1, n2, n3, n4) = self._store()
        ghost = "12345678-abcd-efab-cdef-123456789abc"
        allocs = [Allocation(NodeID=n.ID) for n in (n1, n2, n3, n4)]
        allocs.append(Allocation(NodeID=ghost))
        tainted = tainted_nodes(state, allocs)
        assert len(tainted) == 5
        assert not tainted[n1.ID] and not tainted[n2.ID]
        assert tainted[n3.ID] and tainted[n4.ID] and tainted[ghost]


class TestRetryMax:
    def test_exhausts_then_raises(self):
        """(reference: TestRetryMax)"""
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            return False

        with pytest.raises(SetStatusError):
            retry_max(3, bad)
        assert calls["n"] == 3

        # One progress-based reset doubles the budget once.
        calls["n"] = 0
        state = {"first": True}

        def reset():
            if calls["n"] == 3 and state["first"]:
                state["first"] = False
                return True
            return False

        with pytest.raises(SetStatusError):
            retry_max(3, bad, reset)
        assert calls["n"] == 6

        calls["n"] = 0
        retry_max(3, lambda: calls.__setitem__("n", calls["n"] + 1) or True)
        assert calls["n"] == 1


class TestTasksUpdated:
    """(reference: TestTasksUpdated — every field that forces a
    destructive update, and the service change that must NOT)."""

    MUTATIONS = [
        ("config", lambda t: t.Config.__setitem__("command", "/bin/other")),
        ("task-name", lambda t: setattr(t, "Name", "foo")),
        ("driver", lambda t: setattr(t, "Driver", "foo")),
        ("env", lambda t: t.Env.__setitem__("NEW_ENV", "NEW_VALUE")),
        ("user", lambda t: setattr(t, "User", "foo")),
        ("meta", lambda t: t.Meta.__setitem__("baz", "boom")),
        ("cpu", lambda t: setattr(t.Resources, "CPU", 1337)),
        ("mbits", lambda t: setattr(t.Resources.Networks[0], "MBits", 100)),
        ("dynamic-port-count", lambda t: t.Resources.Networks[0]
         .DynamicPorts.append(Port("extra", 0))),
        ("dynamic-port-label", lambda t: setattr(
            t.Resources.Networks[0].DynamicPorts[0], "Label", "foobar")),
        ("reserved-ports", lambda t: setattr(
            t.Resources.Networks[0], "ReservedPorts",
            [Port(Label="foo", Value=1312)])),
    ]

    def test_identical_groups_not_updated(self):
        j1, j2 = mock.job(), mock.job()
        assert not tasks_updated(j1.TaskGroups[0], j2.TaskGroups[0])

    @pytest.mark.parametrize("name,mutate", MUTATIONS,
                             ids=[m[0] for m in MUTATIONS])
    def test_field_changes_force_update(self, name, mutate):
        j1, j2 = mock.job(), mock.job()
        mutate(j2.TaskGroups[0].Tasks[0])
        assert tasks_updated(j1.TaskGroups[0], j2.TaskGroups[0]), name

    def test_added_task_forces_update(self):
        j1, j2 = mock.job(), mock.job()
        j2.TaskGroups[0].Tasks.append(j2.TaskGroups[0].Tasks[0])
        assert tasks_updated(j1.TaskGroups[0], j2.TaskGroups[0])

    def test_service_change_is_in_place(self):
        """Services update without destroying the alloc (the reference's
        inplaceUpdate relies on this)."""
        j1, j2 = mock.job(), mock.job()
        j2.TaskGroups[0].Tasks[0].Services.append(
            Service(Name="extra", PortLabel="http"))
        assert not tasks_updated(j1.TaskGroups[0], j2.TaskGroups[0])


class TestEvictAndPlace:
    def _ctx(self):
        h = Harness()
        ev = mock.eval()
        job = mock.job()
        plan = ev.make_plan(job)
        return EvalContext(h.state, plan, logger)

    def _allocs(self, n=4):
        return [AllocTuple(f"a{i}", None, Allocation(ID=f"id{i}"))
                for i in range(n)]

    def test_limit_less_than_allocs(self):
        """(reference: TestEvictAndPlace_LimitLessThanAllocs)"""
        ctx = self._ctx()
        diff = DiffResult()
        limit = [2]
        assert evict_and_place(ctx, diff, self._allocs(), "", limit)
        assert limit[0] == 0
        assert len(diff.place) == 2

    def test_limit_equal_to_allocs(self):
        ctx = self._ctx()
        diff = DiffResult()
        limit = [4]
        assert not evict_and_place(ctx, diff, self._allocs(), "", limit)
        assert limit[0] == 0
        assert len(diff.place) == 4

    def test_limit_greater_than_allocs(self):
        ctx = self._ctx()
        diff = DiffResult()
        limit = [6]
        assert not evict_and_place(ctx, diff, self._allocs(), "", limit)
        assert limit[0] == 2
        assert len(diff.place) == 4


class TestSetStatus:
    """(reference: TestSetStatus — plain, next-eval, blocked-eval, and
    failed-TG-metrics variants all land in the planner's eval update)."""

    def test_variants(self):
        ev = mock.eval()

        h = Harness()
        set_status(h, ev, None, None, None, "a", "b")
        assert len(h.evals) == 1
        new = h.evals[0]
        assert (new.ID, new.Status, new.StatusDescription) == (ev.ID, "a",
                                                               "b")

        h = Harness()
        nxt = mock.eval()
        set_status(h, ev, nxt, None, None, "a", "b")
        assert h.evals[0].NextEval == nxt.ID

        h = Harness()
        blocked = mock.eval()
        set_status(h, ev, None, blocked, None, "a", "b")
        assert h.evals[0].BlockedEval == blocked.ID

        h = Harness()
        metrics = {"web": None}
        set_status(h, ev, None, None, metrics, "a", "b")
        assert h.evals[0].FailedTGAllocs == metrics


class TestInplaceUpdate:
    def _setup(self, node_cpu=4000):
        h = Harness()
        node = mock.node()
        node.Resources.CPU = node_cpu
        node.Resources.MemoryMB = 8192
        compute_node_class(node)
        h.upsert("node", node)
        ev = mock.eval()
        job = mock.job()
        job.TaskGroups[0].Tasks[0].Resources.Networks = []
        h.upsert("job", job)
        alloc = Allocation(
            ID="inplace-a", EvalID=ev.ID, NodeID=node.ID, JobID=job.ID,
            Job=job, TaskGroup=job.TaskGroups[0].Name,
            Name=f"{job.Name}.web[0]",
            Resources=Resources(CPU=500, MemoryMB=256),
            TaskResources={"web": Resources(CPU=500, MemoryMB=256)},
            DesiredStatus=AllocDesiredStatusRun)
        h.upsert("allocs", [alloc])
        plan = ev.make_plan(job)
        ctx = EvalContext(h.state, plan, logger)
        stack = GenericStack(ctx, h.tindex, batch=False,
                             rng=random.Random(1))
        stack.set_nodes([node])
        stack.set_job(job)
        return h, ev, job, alloc, plan, ctx, stack

    def test_changed_task_group_is_destructive(self):
        """(reference: TestInplaceUpdate_ChangedTaskGroup)"""
        h, ev, job, alloc, plan, ctx, stack = self._setup()
        tg = _copy_job(job).TaskGroups[0]
        tg.Tasks.append(tg.Tasks[0])  # added task => destructive
        destructive, inplace = attempt_inplace_updates(
            h.state, plan, stack, ev.ID, ctx,
            [AllocTuple(alloc.Name, tg, alloc)])
        assert len(destructive) == 1 and inplace == []
        assert not plan.NodeAllocation

    def test_no_fit_is_destructive(self):
        """(reference: TestInplaceUpdate_NoMatch): same tasks but an ask
        the node cannot fit goes destructive."""
        h, ev, job, alloc, plan, ctx, stack = self._setup(node_cpu=600)
        tg = _copy_job(job).TaskGroups[0]
        tg.Tasks[0].Resources.Networks = []
        tg.Tasks[0].Resources.CPU = 10_000  # cannot fit
        destructive, inplace = attempt_inplace_updates(
            h.state, plan, stack, ev.ID, ctx,
            [AllocTuple(alloc.Name, tg, alloc)])
        assert len(destructive) == 1 and inplace == []

    def test_success_updates_in_place(self):
        """(reference: TestInplaceUpdate_Success): a service-only change
        keeps the alloc, refreshes resources, lands in the plan."""
        h, ev, job, alloc, plan, ctx, stack = self._setup()
        tg = _copy_job(job).TaskGroups[0]
        tg.Tasks[0].Resources.Networks = []
        tg.Tasks[0].Services.append(
            Service(Name="dummy-service", PortLabel="http"))
        destructive, inplace = attempt_inplace_updates(
            h.state, plan, stack, ev.ID, ctx,
            [AllocTuple(alloc.Name, tg, alloc)])
        assert destructive == [] and len(inplace) == 1
        assert inplace[0].Alloc.ID == alloc.ID
        placed = [a for v in plan.NodeAllocation.values() for a in v]
        assert len(placed) == 1
        assert placed[0].EvalID == ev.ID
        assert placed[0].DesiredStatus == AllocDesiredStatusRun


class TestConstraintsAndUpdates:
    def test_task_group_constraints(self):
        """(reference: TestTaskGroupConstraints): TG + task constraints
        combine; drivers dedupe; sizes sum."""
        job = mock.job()
        tg = job.TaskGroups[0]
        tg.Tasks.append(_copy_job(job).TaskGroups[0].Tasks[0])
        tg.Tasks[1].Driver = "docker"
        tg.Tasks[1].Resources = Resources(CPU=100, MemoryMB=100)
        agg = task_group_constraints(tg)
        assert set(agg.drivers) == {"exec", "docker"}
        assert agg.size.CPU == 500 + 100
        assert agg.size.MemoryMB == 256 + 100
        n_task_cons = sum(len(t.Constraints) for t in tg.Tasks)
        assert len(agg.constraints) == len(tg.Constraints) + n_task_cons

    def test_progress_made(self):
        """(reference: TestProgressMade)"""
        assert not progress_made(None)
        assert not progress_made(PlanResult())
        assert progress_made(PlanResult(NodeUpdate={"n": ["x"]}))
        assert progress_made(PlanResult(NodeAllocation={"n": ["x"]}))

    def test_desired_updates(self):
        """(reference: TestDesiredUpdates): per-TG counts of every
        desired-change class for plan annotations."""
        job = mock.job()
        tg = job.TaskGroups[0]
        tup = AllocTuple("n", tg, Allocation(TaskGroup=tg.Name))
        diff = DiffResult(place=[tup, tup], stop=[tup],
                          ignore=[tup, tup, tup], migrate=[tup])
        out = desired_updates(diff, inplace=[tup],
                              destructive=[tup, tup])
        du = out[tg.Name]
        assert (du.Place, du.Stop, du.Ignore, du.Migrate,
                du.InPlaceUpdate, du.DestructiveUpdate) == (2, 1, 3, 1,
                                                            1, 2)


def test_noise_vector_spreads_ties():
    """The reference shuffles nodes so repeated placements spread across
    ties (TestShuffleNodes); our analogue is the per-node tie-break noise
    vector — distinct values, stable shape."""
    from nomad_tpu.scheduler.stack import make_noise_vec

    v1 = make_noise_vec(256, random.Random(1))
    v2 = make_noise_vec(256, random.Random(2))
    assert v1.shape == (256,)
    assert len(set(v1.tolist())) > 200  # essentially all distinct
    assert (v1 != v2).any()
    assert float(v1.max()) < 1e-3
