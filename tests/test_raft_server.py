"""Replicated control-plane tests: full Servers over a Raft cluster
(reference shapes: nomad/leader_test.go — broker/plan-queue enable/disable
across failover; server_test.go multi-node in-process clusters).

The TPU placement path runs only on the leader (workers are leader
singletons here as the scheduling fan-out rides the leader's device-resident
tensor index); followers replicate the FSM so failover rehydrates everything
from local state.
"""


import pytest

from nomad_tpu import mock
from nomad_tpu.raft import InMemTransport, RaftConfig
from nomad_tpu.raft.node import NotLeaderError
from nomad_tpu.raft.transport import BoundTransport
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked cluster suite: one retry

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)


def make_servers(n=3):
    transport = InMemTransport()
    ids = [f"srv{i}" for i in range(n)]
    servers = []
    for nid in ids:
        cfg = ServerConfig(node_id=nid, num_schedulers=1)
        srv = Server(cfg, transport=BoundTransport(transport, nid),
                     peers=list(ids), raft_config=FAST)
        servers.append(srv)
    for srv in servers:
        srv.start()
    return transport, servers


def leader_of(servers):
    leaders = [s for s in servers if s.is_leader() and s._leader]
    return leaders[0] if len(leaders) == 1 else None


class TestReplicatedServer:
    def test_leader_establishes_singletons(self):
        transport, servers = make_servers(3)
        try:
            # Leadership AND the (async) singleton establishment must both
            # land; under suite load the gap between them stretches.
            def leader_ready():
                l = leader_of(servers)
                return (l is not None and l.eval_broker.enabled()
                        and l.plan_queue.enabled())
            assert wait_for(leader_ready)
            leader = leader_of(servers)
            followers = [s for s in servers if s is not leader]
            # A follower that transiently won an early election revokes its
            # singletons once it steps down; convergence is async.
            for f in followers:
                assert wait_for(lambda f=f: not f.eval_broker.enabled())
                assert wait_for(lambda f=f: not f.workers)
        finally:
            for s in servers:
                s.shutdown()

    def test_job_schedules_and_replicates(self):
        transport, servers = make_servers(3)
        try:
            assert wait_for(lambda: leader_of(servers) is not None)
            leader = leader_of(servers)
            for _ in range(2):
                leader.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = leader.job_register(job)
            assert wait_for(lambda: (
                (e := leader.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete), timeout=30)
            assert len(leader.state.allocs_by_job(job.ID)) == 10
            # Followers replicate jobs, evals, and allocations byte-for-byte.
            for f in [s for s in servers if s is not leader]:
                assert wait_for(
                    lambda f=f: f.state.job_by_id(job.ID) is not None)
                assert wait_for(
                    lambda f=f: len(f.state.allocs_by_job(job.ID)) == 10)
        finally:
            for s in servers:
                s.shutdown()

    def test_follower_write_raises_not_leader(self):
        transport, servers = make_servers(3)
        try:
            assert wait_for(lambda: leader_of(servers) is not None)
            leader = leader_of(servers)
            follower = [s for s in servers if s is not leader][0]
            with pytest.raises(NotLeaderError):
                follower.job_register(mock.job())
        finally:
            for s in servers:
                s.shutdown()

    def test_failover_rehydrates_and_schedules(self):
        """Kill the leader mid-flight; the new leader restores broker/plan
        queue from replicated state and finishes scheduling work
        (reference: leader.go:107-243 establishLeadership + restoreEvals)."""
        transport, servers = make_servers(3)
        try:
            assert wait_for(lambda: leader_of(servers) is not None)
            leader = leader_of(servers)
            for _ in range(2):
                leader.node_register(mock.node())
            job1 = mock.job()
            eval_id, _, _ = leader.job_register(job1)
            assert wait_for(lambda: (
                (e := leader.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete), timeout=30)

            # Hard-kill the leader (no graceful transfer).
            transport.take_down(leader.config.node_id)
            leader.raft.node.shutdown()
            rest = [s for s in servers if s is not leader]
            assert wait_for(lambda: leader_of(rest) is not None, timeout=20)
            new_leader = leader_of(rest)
            assert new_leader.eval_broker.enabled()

            # The new leader carries the replicated cluster state and can
            # schedule fresh work end to end. Its FSM finishes applying the
            # replicated tail after the barrier commits it.
            assert wait_for(
                lambda: new_leader.state.job_by_id(job1.ID) is not None)
            assert wait_for(
                lambda: len(new_leader.state.allocs_by_job(job1.ID)) == 10)
            # Fresh capacity registered through the NEW leader: writes work
            # post-failover and job2 has room (job1 filled the first two
            # nodes).
            for _ in range(3):
                new_leader.node_register(mock.node())
            job2 = mock.job()
            eval2, _, _ = new_leader.job_register(job2)
            assert wait_for(lambda: (
                (e := new_leader.state.eval_by_id(eval2)) is not None
                and e.Status == EvalStatusComplete), timeout=30)
            assert len(new_leader.state.allocs_by_job(job2.ID)) == 10
        finally:
            for s in servers:
                s.shutdown()
