"""Gossip memberlist tests (reference analogue: the memberlist/serf
behavior nomad/serf.go depends on — join, convergence, failure
detection, graceful leave, tag updates)."""

import threading
import time

import pytest

from nomad_tpu.gossip import (
    ALIVE,
    DEAD,
    EVENT_FAILED,
    EVENT_JOIN,
    EVENT_LEAVE,
    EVENT_UPDATE,
    GossipConfig,
    Memberlist,
)


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked cluster suite: one retry

def make(name, events=None, tags=None):
    cb = None
    if events is not None:
        cb = lambda ev, m: events.append((ev, m.name))
    ml = Memberlist(name, tags=tags or {}, config=GossipConfig.fast(),
                    on_event=cb)
    ml.start()
    return ml


def test_join_and_convergence():
    mls = []
    try:
        a = make("a")
        mls.append(a)
        for name in ("b", "c", "d"):
            m = make(name)
            mls.append(m)
            assert m.join([f"{a.addr}:{a.port}"]) == 1
        for m in mls:
            wait_for(lambda m=m: m.num_alive() == 4, msg=f"{m.name} sees 4")
            assert sorted(x.name for x in m.alive_members()) == [
                "a", "b", "c", "d"]
    finally:
        for m in mls:
            m.shutdown()


def test_join_events_fire():
    events = []
    a = make("a", events=events)
    b = make("b")
    try:
        b.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: (EVENT_JOIN, "b") in events, msg="join event")
    finally:
        a.shutdown()
        b.shutdown()


def test_failure_detection():
    events = []
    a = make("a", events=events)
    b = make("b")
    c = make("c")
    try:
        b.join([f"{a.addr}:{a.port}"])
        c.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: a.num_alive() == 3, msg="cluster of 3")
        # hard-kill c: sockets closed, no leave broadcast
        c.shutdown()
        wait_for(lambda: (EVENT_FAILED, "c") in events, timeout=10.0,
                 msg="failure detected")
        states = {m.name: m.state for m in a.members()}
        assert states["c"] == DEAD
        # b converges to the same verdict via gossip
        wait_for(lambda: any(m.name == "c" and m.state == DEAD
                             for m in b.members()), timeout=10.0,
                 msg="b learns of c's death")
    finally:
        a.shutdown()
        b.shutdown()


def test_graceful_leave():
    events = []
    a = make("a", events=events)
    b = make("b")
    try:
        b.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: a.num_alive() == 2, msg="joined")
        b.leave()
        wait_for(lambda: (EVENT_LEAVE, "b") in events, msg="leave event")
        assert (EVENT_FAILED, "b") not in events
    finally:
        a.shutdown()
        b.shutdown()


def test_tag_update_propagates():
    events = []
    a = make("a", events=events)
    b = make("b", tags={"port": "1"})
    try:
        b.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: a.num_alive() == 2, msg="joined")
        b.set_tags({"port": "2"})
        wait_for(lambda: (EVENT_UPDATE, "b") in events, msg="update event")
        tags = {m.name: m.tags for m in a.members()}
        assert tags["b"] == {"port": "2"}
    finally:
        a.shutdown()
        b.shutdown()


def test_refutation_keeps_live_member_alive():
    """A falsely-suspected member refutes by raising its incarnation."""
    a = make("a")
    b = make("b")
    try:
        b.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: a.num_alive() == 2, msg="joined")
        # inject a false suspicion of b directly into a's FSM
        binfo = [m for m in a.members() if m.name == "b"][0]
        a._on_suspect("b", binfo.incarnation, "a")
        # b must refute before the suspicion deadline; it stays alive
        time.sleep(a._suspicion_timeout() + 0.3)
        states = {m.name: m.state for m in a.members()}
        assert states["b"] == ALIVE
        new_inc = [m for m in a.members() if m.name == "b"][0].incarnation
        assert new_inc > binfo.incarnation
    finally:
        a.shutdown()
        b.shutdown()


def test_rejoin_after_failure():
    a = make("a")
    b = make("b")
    try:
        b.join([f"{a.addr}:{a.port}"])
        wait_for(lambda: a.num_alive() == 2, msg="joined")
        b.shutdown()
        wait_for(lambda: any(m.name == "b" and m.state == DEAD
                             for m in a.members()), timeout=10.0,
                 msg="b declared dead")
        # a new instance under the same name rejoins
        b2 = make("b")
        try:
            b2.join([f"{a.addr}:{a.port}"])
            wait_for(lambda: a.num_alive() == 2, timeout=10.0,
                     msg="b rejoined")
        finally:
            b2.shutdown()
    finally:
        a.shutdown()
        b.shutdown()
