"""Periodic dispatcher parity grid (reference: nomad/periodic_test.go —
the dispatcher-level cases beyond test_server.py's single e2e dispatch:
tracking add/update/remove, force-run, multi-launch ordering, same-time
coalescing, and heap ordering semantics)."""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.periodic import PeriodicDispatch, derive_job, \
    derived_job_id
from nomad_tpu.structs import PeriodicConfig
from nomad_tpu.structs.structs import JobTypeBatch, PeriodicSpecTest

from helpers import wait_for  # noqa: E402


class Capture:
    def __init__(self):
        self.launches = []
        self.event = threading.Event()

    def __call__(self, job, launch_time):
        self.launches.append((job.ID, launch_time))
        self.event.set()


def periodic_job(*times, job=None):
    job = job or mock.job()
    job.Type = JobTypeBatch
    job.Periodic = PeriodicConfig(
        Enabled=True, SpecType=PeriodicSpecTest,
        Spec=",".join(str(t) for t in times))
    return job


@pytest.fixture
def dispatcher():
    cap = Capture()
    pd = PeriodicDispatch(cap)
    pd.set_enabled(True)
    yield pd, cap
    pd.set_enabled(False)


class TestPeriodicDispatch:
    def test_add_non_periodic_untracked(self, dispatcher):
        """(reference: TestPeriodicDispatch_Add_NonPeriodic)"""
        pd, _ = dispatcher
        pd.add(mock.job())
        assert pd.tracked() == []

    def test_add_update_job(self, dispatcher):
        """(reference: TestPeriodicDispatch_Add_UpdateJob): re-adding
        the same ID replaces the tracked job, not duplicates it."""
        pd, _ = dispatcher
        job = periodic_job(time.time() + 3600)
        pd.add(job)
        assert [j.ID for j in pd.tracked()] == [job.ID]
        updated = periodic_job(time.time() + 7200, job=job.copy())
        pd.add(updated)
        tracked = pd.tracked()
        assert [j.ID for j in tracked] == [job.ID]
        assert tracked[0].Periodic.Spec == updated.Periodic.Spec

    def test_add_disabled_update_removes(self, dispatcher):
        """(reference: TestPeriodicDispatch_Add_RemoveJob): updating a
        tracked job to non-periodic untracks it."""
        pd, _ = dispatcher
        job = periodic_job(time.time() + 3600)
        pd.add(job)
        assert pd.tracked()
        plain = job.copy()
        plain.Periodic = None
        pd.add(plain)
        assert pd.tracked() == []

    def test_add_triggers_update(self, dispatcher):
        """(reference: TestPeriodicDispatch_Add_TriggersUpdate): re-add
        with an EARLIER launch time fires at the new time, not the old."""
        pd, cap = dispatcher
        job = periodic_job(time.time() + 3600)
        pd.add(job)
        pd.add(periodic_job(time.time() + 0.2, job=job.copy()))
        assert cap.event.wait(10)
        assert cap.launches[0][0] == job.ID

    def test_remove_untracked_is_noop(self, dispatcher):
        """(reference: TestPeriodicDispatch_Remove_Untracked)"""
        pd, _ = dispatcher
        pd.remove("nope")  # must not raise

    def test_remove_tracked_prevents_launch(self, dispatcher):
        """(reference: TestPeriodicDispatch_Remove_Tracked +
        Remove_TriggersUpdate): a removed job never fires."""
        pd, cap = dispatcher
        job = periodic_job(time.time() + 0.3)
        pd.add(job)
        pd.remove(job.ID)
        assert pd.tracked() == []
        assert not cap.event.wait(0.8)
        assert cap.launches == []

    def test_force_run_untracked_raises(self, dispatcher):
        """(reference: TestPeriodicDispatch_ForceRun_Untracked)"""
        pd, _ = dispatcher
        with pytest.raises(KeyError):
            pd.force_run("nope")

    def test_force_run_tracked_dispatches(self, dispatcher):
        """(reference: TestPeriodicDispatch_ForceRun_Tracked)"""
        pd, cap = dispatcher
        job = periodic_job(time.time() + 3600)
        pd.add(job)
        pd.force_run(job.ID)
        assert cap.launches and cap.launches[0][0] == job.ID

    def test_run_multiple_launches_in_order(self, dispatcher):
        """(reference: TestPeriodicDispatch_Run_Multiple): successive
        spec times fire in order for the same job."""
        pd, cap = dispatcher
        now = time.time()
        # Wide gap between spec times: next() only returns times strictly
        # after the FIRST ACTUAL fire, so a loaded box firing late must
        # not skip past the second slot.
        job = periodic_job(now + 0.2, now + 1.5)
        pd.add(job)
        assert wait_for(lambda: len(cap.launches) >= 2, timeout=10)
        assert [l[0] for l in cap.launches[:2]] == [job.ID, job.ID]
        assert cap.launches[0][1] <= cap.launches[1][1]

    def test_run_same_time_fires_both_jobs(self, dispatcher):
        """(reference: TestPeriodicDispatch_Run_SameTime)"""
        pd, cap = dispatcher
        at = time.time() + 0.25
        j1, j2 = periodic_job(at), periodic_job(at)
        pd.add(j1)
        pd.add(j2)
        assert wait_for(lambda: len(cap.launches) >= 2, timeout=10)
        assert {l[0] for l in cap.launches} == {j1.ID, j2.ID}

    def test_disabled_add_is_noop(self):
        """(reference: periodic.go SetEnabled(false) semantics)"""
        cap = Capture()
        pd = PeriodicDispatch(cap)
        pd.add(periodic_job(time.time() + 0.1))
        assert pd.tracked() == []


class TestDerivedJobs:
    def test_derived_id_and_job(self):
        """(reference: periodic.go deriveJob + TestPeriodicDispatch's
        child naming): the child is non-periodic, parented, and named
        with the launch timestamp."""
        parent = periodic_job(time.time() + 3600)
        launch = 1_700_000_000.0
        child = derive_job(parent, launch)
        assert child.ID == derived_job_id(parent.ID, launch)
        assert child.ID.startswith(parent.ID + "/periodic-")
        assert not child.is_periodic()
        assert child.ParentID == parent.ID
