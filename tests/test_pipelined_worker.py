"""PipelinedWorker: the windowed device-chained served scheduling path.

Covers: burst placement through the fast path (correctness + no
oversubscription), mixed fast/slow windows, blocked-eval creation on
exhaustion through the fast path, and parity of outcomes with the per-eval
GenericScheduler (reference behavior model: nomad/worker.go + the plan
applier's re-verification making optimistic chaining safe)."""


import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete
from nomad_tpu.tensor.node_table import alloc_vec, resources_vec


from helpers import wait_for  # noqa: E402

def simple_job(count=4, cpu=None, mem=None):
    """mock.job() without networks (ports are host-side; these tests target
    the device placement path) — services referencing ports go with them."""
    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = count
    task = tg.Tasks[0]
    task.Resources.Networks = []
    task.Services = []
    if cpu is not None:
        task.Resources.CPU = cpu
    if mem is not None:
        task.Resources.MemoryMB = mem
    return job


def make_server(**overrides):
    cfg = ServerConfig(num_schedulers=1, pipelined_scheduling=True,
                       scheduler_window=16, **overrides)
    srv = Server(cfg)
    srv.establish_leadership()
    return srv


def total_usage_by_node(state):
    usage = {}
    for alloc in state.allocs():
        if alloc.terminal_status():
            continue
        v = usage.setdefault(alloc.NodeID, np.zeros(5, dtype=np.float64))
        v += alloc_vec(alloc)
    return usage


class TestPipelinedBurst:
    def test_burst_of_jobs_all_place_fast_path(self):
        """A registration storm drains through the device-chained window and
        every eval completes with committed allocations."""
        srv = make_server()
        try:
            for _ in range(20):
                srv.node_register(mock.node())
            jobs = [simple_job(count=4) for _ in range(12)]
            eval_ids = [srv.job_register(j)[0] for j in jobs]
            assert wait_for(lambda: all(
                (e := srv.state.eval_by_id(eid)) is not None
                and e.Status == EvalStatusComplete for eid in eval_ids))
            for job in jobs:
                allocs = [a for a in srv.state.allocs_by_job(job.ID)
                          if not a.terminal_status()]
                assert len(allocs) == 4, job.ID
            # The fast path actually ran (not everything fell back).
            stats = srv.workers[0].stats
            assert stats["fast"] > 0
        finally:
            srv.shutdown()

    def test_no_oversubscription_after_burst(self):
        """Optimistic chaining must never commit more than a node's capacity
        (the plan applier re-verifies every placement)."""
        srv = make_server()
        try:
            nodes = []
            for _ in range(4):
                n = mock.node()
                nodes.append(n)
                srv.node_register(n)
            # Enough demand to pack nodes near-full: 4 nodes x 4000 cpu,
            # each alloc asks 500 cpu -> exactly 32 fit.
            jobs = [simple_job(count=4, cpu=500, mem=256)
                    for _ in range(10)]
            eval_ids = [srv.job_register(j)[0] for j in jobs]
            assert wait_for(lambda: all(
                srv.state.eval_by_id(eid) is not None
                and srv.state.eval_by_id(eid).Status not in ("pending",)
                for eid in eval_ids), timeout=20)
            usage = total_usage_by_node(srv.state)
            caps = {n.ID: resources_vec(n.Resources) for n in nodes}
            for node_id, used in usage.items():
                assert np.all(used <= caps[node_id] + 1e-6), (
                    f"node {node_id} oversubscribed: {used} > {caps[node_id]}")
        finally:
            srv.shutdown()

    def test_exhaustion_creates_blocked_eval_via_fast_path(self):
        srv = make_server()
        try:
            n = mock.node()
            n.Resources.CPU = 1000
            srv.node_register(n)
            job = simple_job(count=6, cpu=500)  # 6 x 500 cpu > 1000 cpu
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: (
                (e := srv.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete))
            ev = srv.state.eval_by_id(eval_id)
            assert ev.FailedTGAllocs, "exhaustion must be recorded"
            assert ev.BlockedEval, "a blocked eval must be spawned"
            blocked = srv.state.eval_by_id(ev.BlockedEval)
            assert blocked is not None
            # Capacity arrives: the blocked eval unblocks and places the rest.
            n2 = mock.node()
            srv.node_register(n2)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()]) == 6, timeout=20)
        finally:
            srv.shutdown()

    def test_update_takes_slow_path_and_still_works(self):
        """A job update (destructive) is not pure placement: it must route
        through the per-eval GenericScheduler and still converge."""
        srv = make_server()
        try:
            for _ in range(3):
                srv.node_register(mock.node())
            job = simple_job(count=3)
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()]) == 3)
            # Destructive update: change the task command.
            job2 = job.copy()
            job2.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
            srv.job_register(job2)
            assert wait_for(lambda: srv.workers[0].stats["slow"] > 0,
                            timeout=20)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()
                and a.Job is None or True]) >= 3, timeout=20)
        finally:
            srv.shutdown()

    def test_parity_with_per_eval_worker(self):
        """Same workload through pipelined and per-eval servers lands the
        same number of allocations with the same per-job placement counts."""
        results = {}
        for pipelined in (True, False):
            srv = Server(ServerConfig(num_schedulers=1,
                                      pipelined_scheduling=pipelined))
            srv.establish_leadership()
            try:
                for i in range(8):
                    srv.node_register(mock.node())
                placed = {}
                eval_ids = []
                jobs = []
                for _ in range(6):
                    job = simple_job(count=5)
                    jobs.append(job)
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(lambda: all(
                    (e := srv.state.eval_by_id(eid)) is not None
                    and e.Status == EvalStatusComplete
                    for eid in eval_ids), timeout=20)
                for job in jobs:
                    placed[job.ID] = len([
                        a for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status()])
                results[pipelined] = sorted(placed.values())
            finally:
                srv.shutdown()
        assert results[True] == results[False] == [5] * 6


class TestChainRebase:
    def test_row_identity_change_rebases_chain(self):
        """A freed row reused by a new node mid-storm must invalidate the
        device usage chain: shape alone doesn't change on free-list reuse,
        so the worker tracks the table's row_epoch."""
        srv = make_server()
        try:
            nodes = [mock.node() for _ in range(4)]
            for n in nodes:
                srv.node_register(n)
            w = srv.workers[0]
            nt = srv.tindex.nt

            # Simulate a live chain built against the current table: one
            # published window in flight, tail validated at this epoch.
            chain = np.zeros((nt.n_rows, 5), dtype=np.float32)
            arb = w._arbiter
            lease = arb.acquire()
            arb.publish(lease, chain)
            lease = arb.acquire()
            assert lease.chain is not None  # in flight: chain is kept
            arb.publish(lease, chain)

            # Node leaves; its row goes to the free list (no resize).
            nt.remove_node(nodes[0].ID)
            lease = arb.acquire()
            assert lease.chain is None, (
                "chain must rebase after a row identity change")
            arb.abort(lease)
        finally:
            srv.shutdown()


class TestStalePhantomUsage:
    """A record that goes stale/fallback mid-window leaves its chained
    kernel placements as PHANTOM usage: later evals of the window were
    squeezed by capacity that never commits. The worker must re-run those
    evals on the exact path (not park them as blocked evals that no
    capacity event will ever unblock) and rebase the next window's chain.
    (VERDICT r3 weak #4 / ADVICE r2 #3.)"""

    def test_redelivered_eval_does_not_phantom_block_the_window(self):
        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.structs.structs import EvalStatusBlocked

        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=16))
        srv.establish_leadership()
        try:
            node = mock.node()
            node.Resources.CPU = 1000
            node.Resources.MemoryMB = 4000
            node.Reserved = None
            srv.node_register(node)

            # Two jobs that cannot BOTH fit: the chained window has B see
            # A's (ultimately phantom) 600cpu placement.
            job_a = simple_job(count=1, cpu=600, mem=100)
            job_b = simple_job(count=1, cpu=600, mem=100)
            eval_a, _, _ = srv.job_register(job_a)
            eval_b, _, _ = srv.job_register(job_b)

            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=16)
            batch = w._dequeue_window()
            assert {ev.ID for ev, _ in batch} == {eval_a, eval_b}
            # Deterministic chain order: A first, then B.
            batch.sort(key=lambda p: 0 if p[0].ID == eval_a else 1)
            work = w._dispatch_window(batch)
            assert work is not None and len(work.fast) == 2

            # Redeliver A between dispatch and build (nack-timeout shape):
            # its token is no longer outstanding, so the build stage must
            # mark it stale at plan-enqueue.
            rec_a = work.fast[0]
            srv.eval_broker.nack(rec_a.ev.ID, rec_a.token)

            work.packed = w._drain_window(work)
            w._finish_fast(work)

            # A was abandoned (stale), not acked, not planned.
            assert rec_a.stale
            assert w.stats.get("stale", 0) == 1
            assert work.published  # fast evals dispatched: window in flight
            # B must NOT be parked as a blocked eval on phantom usage: the
            # node really has 1000 cpu free, so the exact-path re-run
            # places it.
            e_b = srv.state.eval_by_id(eval_b)
            assert e_b is not None and e_b.Status == EvalStatusComplete
            allocs_b = [a for a in srv.state.allocs_by_job(job_b.ID)
                        if not a.terminal_status()]
            assert len(allocs_b) == 1
            assert not [e for e in srv.state.evals_by_job(job_b.ID)
                        if e.Status == EvalStatusBlocked]
            # The next window must rebase off committed state instead of
            # inheriting A's phantom usage (the arbiter is marked dirty,
            # and a fresh lease — what run()'s next dispatch takes after
            # the build stage retires this window — carries no chain).
            assert w._arbiter.dirty
            w._arbiter.finish_window()  # what _build_loop's finally does
            lease = w._arbiter.acquire()
            assert lease.chain is None
            w._arbiter.abort(lease)
        finally:
            srv.shutdown()

    def test_inflight_window_detects_taint_from_earlier_window(self):
        """Pipelining keeps windows in flight: window 2 dispatches chained
        on window 1's device tail BEFORE window 1's build discovers its
        record went stale. Window 2 must detect the taint at finish time
        (taint sequence) and re-run its squeezed evals instead of parking
        them blocked."""
        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.structs.structs import EvalStatusBlocked

        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=16))
        srv.establish_leadership()
        try:
            node = mock.node()
            node.Resources.CPU = 1000
            node.Resources.MemoryMB = 4000
            node.Reserved = None
            srv.node_register(node)

            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=16)

            job_a = simple_job(count=1, cpu=600, mem=100)
            eval_a, _, _ = srv.job_register(job_a)
            batch1 = w._dequeue_window()
            work1 = w._dispatch_window(batch1)
            assert work1 is not None and len(work1.fast) == 1
            assert work1.published  # dispatch published the window's tail

            # Window 2 dispatches on window 1's (soon-phantom) tail.
            job_b = simple_job(count=1, cpu=600, mem=100)
            eval_b, _, _ = srv.job_register(job_b)
            batch2 = w._dequeue_window()
            work2 = w._dispatch_window(batch2)
            assert work2 is not None and len(work2.fast) == 1
            assert work2.chained

            # Window 1's record goes stale (redelivered) before its build.
            rec_a = work1.fast[0]
            srv.eval_broker.nack(rec_a.ev.ID, rec_a.token)
            work1.packed = w._drain_window(work1)
            w._finish_fast(work1)
            assert rec_a.stale

            # Window 2 finishes AFTER the taint: its squeezed eval re-runs
            # on the exact path and places for real.
            work2.packed = w._drain_window(work2)
            w._finish_fast(work2)
            e_b = srv.state.eval_by_id(eval_b)
            assert e_b is not None and e_b.Status == EvalStatusComplete
            assert len([a for a in srv.state.allocs_by_job(job_b.ID)
                        if not a.terminal_status()]) == 1
            assert not [e for e in srv.state.evals_by_job(job_b.ID)
                        if e.Status == EvalStatusBlocked]
        finally:
            srv.shutdown()


class TestCrossWorkerTaintBarrier:
    def test_quarantine_waits_for_predecessor_taint(self):
        """TWO workers share the chain arbiter: worker B's window rides
        worker A's (soon-phantom) tail, and B's build races ahead of A's.
        B must BLOCK at the chain-order barrier until A announces its
        taint — otherwise B reads a stale taint sequence and parks its
        squeezed eval as a blocked eval no capacity event will unblock."""
        import threading

        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.structs.structs import EvalStatusBlocked
        from nomad_tpu.tensor.node_table import ChainArbiter

        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=16))
        srv.establish_leadership()
        try:
            node = mock.node()
            node.Resources.CPU = 1000
            node.Resources.MemoryMB = 4000
            node.Reserved = None
            srv.node_register(node)

            arb = ChainArbiter(srv.tindex.nt)
            wa = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                 srv.blocked_evals, srv.tindex,
                                 ["service", "batch", "system"], window=16,
                                 chain_arbiter=arb)
            wb = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                 srv.blocked_evals, srv.tindex,
                                 ["service", "batch", "system"], window=16,
                                 chain_arbiter=arb)

            job_a = simple_job(count=1, cpu=600, mem=100)
            eval_a, _, _ = srv.job_register(job_a)
            work_a = wa._dispatch_window(wa._dequeue_window())
            assert work_a is not None and work_a.published

            job_b = simple_job(count=1, cpu=600, mem=100)
            eval_b, _, _ = srv.job_register(job_b)
            work_b = wb._dispatch_window(wb._dequeue_window())
            assert work_b is not None and work_b.chained
            assert work_b.chain_seq == work_a.chain_seq + 1

            # A's record goes stale (redelivered) before either builds.
            rec_a = work_a.fast[0]
            srv.eval_broker.nack(rec_a.ev.ID, rec_a.token)

            # B's build runs FIRST — it must park at the barrier.
            work_b.packed = wb._drain_window(work_b)
            b_done = threading.Event()

            def finish_b():
                wb._finish_fast(work_b)
                b_done.set()

            t = threading.Thread(target=finish_b, daemon=True,
                                 name="test-finish-b")
            t.start()
            assert not b_done.wait(0.5), \
                "B settled before A announced its taint"

            # A's build settles: stale record, taint raised, barrier opens.
            work_a.packed = wa._drain_window(work_a)
            wa._finish_fast(work_a)
            assert rec_a.stale
            assert b_done.wait(10), "B never unblocked from the barrier"
            t.join(5)

            # B detected the external taint and re-ran on the exact path:
            # placed for real, not parked blocked on phantom usage.
            e_b = srv.state.eval_by_id(eval_b)
            assert e_b is not None and e_b.Status == EvalStatusComplete
            assert len([a for a in srv.state.allocs_by_job(job_b.ID)
                        if not a.terminal_status()]) == 1
            assert not [e for e in srv.state.evals_by_job(job_b.ID)
                        if e.Status == EvalStatusBlocked]
        finally:
            srv.shutdown()


class TestFastSlowEquivalence:
    """A fixed-seed window run through _finish_fast must commit the same
    placements (node, scores, ports) as the same evals run through
    _process_slow — the fast path only accelerates evals whose outcome is
    provably identical. One record is force-failed at plan commit
    (plan.apply.commit failpoint) so the fallback/phantom-taint re-run is
    part of the compared window, not a separate test."""

    def _fleet(self, n=6):
        return [mock.node() for _ in range(n)]

    def _jobs(self):
        from nomad_tpu.structs import NetworkResource
        from nomad_tpu.structs.structs import Port

        jobs = [simple_job(count=3, cpu=120 + 10 * i, mem=64)
                for i in range(4)]
        # One group WITH a (static, deterministic) port ask: exercises the
        # exact per-placement network path on both sides.
        pj = simple_job(count=1, cpu=80, mem=32)
        task = pj.TaskGroups[0].Tasks[0]
        task.Resources.Networks = [
            NetworkResource(MBits=1,
                            ReservedPorts=[Port("http", 12345)])]
        jobs.append(pj)
        return jobs

    def _placements(self, srv, jobs):
        out = {}
        for job in jobs:
            allocs = sorted(
                (a for a in srv.state.allocs_by_job(job.ID)
                 if not a.terminal_status()), key=lambda a: a.Name)
            out[job.ID] = [
                (a.Name, a.NodeID,
                 round((a.Metrics.Scores or {}).get(
                     f"{a.NodeID}.binpack", 0.0), 4),
                 sorted((p.Label, p.Value)
                        for r in a.TaskResources.values()
                        for net in r.Networks
                        for p in net.ReservedPorts))
                for a in allocs]
        return out

    def test_window_matches_per_eval_path(self, monkeypatch):
        import numpy as np

        from nomad_tpu.resilience import failpoints
        from nomad_tpu.server.pipelined_worker import PipelinedWorker

        # Zero tie-break noise on BOTH paths: placements become a pure
        # function of the (identical) fleet + submission order.
        monkeypatch.setattr(
            "nomad_tpu.scheduler.stack.make_noise_vec",
            lambda n_rows, rng: np.zeros(n_rows, dtype=np.float32))

        fleet = self._fleet()
        jobs = self._jobs()
        # The forced-fallback eval rides its OWN second window: a commit
        # failure re-runs the record AFTER the rest of its window commits,
        # so window membership is what keeps the usage each eval observes
        # identical between the two paths.
        fallback_job = simple_job(count=2, cpu=90, mem=48)
        results = {}
        try:
            for mode in ("fast", "slow"):
                srv = Server(ServerConfig(num_schedulers=0,
                                          pipelined_scheduling=True,
                                          scheduler_window=16))
                srv.establish_leadership()
                try:
                    for node in fleet:
                        srv.node_register(node.copy())
                    for job in jobs:
                        srv.job_register(job.copy())
                    w = PipelinedWorker(
                        srv.raft, srv.eval_broker, srv.plan_queue,
                        srv.blocked_evals, srv.tindex,
                        ["service", "batch", "system"], window=16)
                    batch = w._dequeue_window()
                    assert len(batch) == len(jobs)
                    batch.sort(key=lambda p: p[0].JobID)
                    if mode == "fast":
                        work = w._dispatch_window(batch)
                        assert work is not None
                        assert len(work.fast) == len(jobs)
                        work.packed = w._drain_window(work)
                        w._finish_fast(work)
                        assert w.stats["fast"] == len(jobs)
                    else:
                        for ev, token in batch:
                            w._process_slow(ev, token)

                    # Second window: ONE record whose plan commit is
                    # forced to fail — _finish_fast must re-run it on the
                    # exact path (the phantom-taint machinery raises
                    # _chain_dirty so the next window rebases).
                    srv.job_register(fallback_job.copy())
                    batch2 = w._dequeue_window()
                    assert len(batch2) == 1
                    if mode == "fast":
                        failpoints.arm("plan.apply.commit", "error",
                                       count=1)
                        work2 = w._dispatch_window(batch2)
                        assert work2 is not None and len(work2.fast) == 1
                        work2.packed = w._drain_window(work2)
                        w._finish_fast(work2)
                        assert w.stats["fallback"] == 1, \
                            "the forced-fallback record never re-ran"
                        assert w._arbiter.dirty, \
                            "fallback must taint the chain for rebase"
                    else:
                        for ev, token in batch2:
                            w._process_slow(ev, token)
                    results[mode] = self._placements(
                        srv, jobs + [fallback_job])
                finally:
                    srv.shutdown()
        finally:
            failpoints.disarm_all()
        assert results["fast"] == results["slow"]
        # Non-vacuous: real scores and the static port came through.
        flat = [t for allocs in results["fast"].values() for t in allocs]
        assert any(score > 0 for _, _, score, _ in flat)
        assert any(ports == [("http", 12345)] for _, _, _, ports in flat)


class TestWorkerScalingEquivalence:
    """ISSUE 5 satellite: the SAME fixed storm run with 1 and with 2
    pipelined workers (sharing one ChainArbiter via the server) must end
    in the same place: no lost evals, no double-placed allocs, and an
    IDENTICAL final placed count. The storm exhausts the fleet with
    uniform demands, so the capacity-limited total is order-independent
    — window splits between workers cannot change it, only break it."""

    N_JOBS = 8
    PER_JOB = 3
    CPU = 100  # 4 nodes x 500 cpu / 100 = 20 slots for 24 asks

    def _fleet(self):
        nodes = []
        for _ in range(4):
            node = mock.node()
            node.Resources.CPU = 500
            node.Resources.MemoryMB = 2000
            node.Reserved = None
            nodes.append(node)
        return nodes

    def test_one_vs_two_workers_same_storm(self):
        from nomad_tpu.structs.structs import EvalStatusBlocked

        placed_totals = {}
        for n_workers in (1, 2):
            srv = Server(ServerConfig(num_schedulers=n_workers,
                                      pipelined_scheduling=True,
                                      scheduler_window=8))
            srv.establish_leadership()
            try:
                for node in self._fleet():
                    srv.node_register(node)
                jobs = [simple_job(count=self.PER_JOB, cpu=self.CPU, mem=10)
                        for _ in range(self.N_JOBS)]
                eval_ids = [srv.job_register(j)[0] for j in jobs]
                # No lost evals: every one of the storm's evals reaches a
                # terminal status even though 4 of the 24 asks exhaust.
                assert wait_for(lambda: all(
                    (e := srv.state.eval_by_id(eid)) is not None
                    and e.Status == EvalStatusComplete
                    for eid in eval_ids), timeout=30)

                live = [a for a in srv.state.allocs()
                        if not a.terminal_status()]
                # No double-placed allocs: unique IDs, nothing over any
                # job's ask, nothing over any node's capacity.
                assert len({a.ID for a in live}) == len(live)
                for job in jobs:
                    per_job = [a for a in live if a.JobID == job.ID]
                    assert len(per_job) <= self.PER_JOB, job.ID
                for node_id, used in total_usage_by_node(srv.state).items():
                    assert used[0] <= 500 + 1e-6, (node_id, used)
                # The overflow is parked blocked, not lost or failed.
                blocked = [e for e in srv.state.evals()
                           if e.Status == EvalStatusBlocked]
                assert blocked, "exhausted asks must park as blocked evals"
                placed_totals[n_workers] = len(live)
            finally:
                srv.shutdown()
        # Identical final placed count, and exactly the capacity bound:
        # 4 nodes x (500 cpu / 100 cpu-per-alloc) = 20.
        assert placed_totals[1] == placed_totals[2] == 20, placed_totals


class TestWindowFusion:
    def test_interleaved_preps_fuse_and_place_correctly(self):
        """A window mixing two job shapes (A,B,A,B...) fuses only
        consecutive shared-prep runs; placements still match totals and
        nothing oversubscribes."""
        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=32,
                                  host_placement=False))
        srv.establish_leadership()
        try:
            from nomad_tpu.server.pipelined_worker import PipelinedWorker

            for _ in range(10):
                srv.node_register(mock.node())
            jobs = []
            for i in range(8):
                if i % 2 == 0:
                    job = simple_job(count=2, cpu=100, mem=64)
                else:
                    job = simple_job(count=3, cpu=150, mem=96)
                jobs.append(job)
                srv.job_register(job)
            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=32,
                                host_placement=False)
            batch = w._dequeue_window()
            assert len(batch) == 8
            work = w._dispatch_window(batch)
            assert work is not None and len(work.fast) == 8
            work.packed = w._drain_window(work)
            w._finish_fast(work)
            for job in jobs:
                want = job.TaskGroups[0].Count
                got = len([a for a in srv.state.allocs_by_job(job.ID)
                           if not a.terminal_status()])
                assert got == want, (job.ID, got, want)
            assert w.stats.get("multi", 0) >= 1  # at least one fused run
        finally:
            srv.shutdown()
