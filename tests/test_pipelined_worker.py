"""PipelinedWorker: the windowed device-chained served scheduling path.

Covers: burst placement through the fast path (correctness + no
oversubscription), mixed fast/slow windows, blocked-eval creation on
exhaustion through the fast path, and parity of outcomes with the per-eval
GenericScheduler (reference behavior model: nomad/worker.go + the plan
applier's re-verification making optimistic chaining safe)."""


import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete
from nomad_tpu.tensor.node_table import alloc_vec, resources_vec


from helpers import wait_for  # noqa: E402

def simple_job(count=4, cpu=None, mem=None):
    """mock.job() without networks (ports are host-side; these tests target
    the device placement path) — services referencing ports go with them."""
    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = count
    task = tg.Tasks[0]
    task.Resources.Networks = []
    task.Services = []
    if cpu is not None:
        task.Resources.CPU = cpu
    if mem is not None:
        task.Resources.MemoryMB = mem
    return job


def make_server(**overrides):
    cfg = ServerConfig(num_schedulers=1, pipelined_scheduling=True,
                       scheduler_window=16, **overrides)
    srv = Server(cfg)
    srv.establish_leadership()
    return srv


def total_usage_by_node(state):
    usage = {}
    for alloc in state.allocs():
        if alloc.terminal_status():
            continue
        v = usage.setdefault(alloc.NodeID, np.zeros(5, dtype=np.float64))
        v += alloc_vec(alloc)
    return usage


class TestPipelinedBurst:
    def test_burst_of_jobs_all_place_fast_path(self):
        """A registration storm drains through the device-chained window and
        every eval completes with committed allocations."""
        srv = make_server()
        try:
            for _ in range(20):
                srv.node_register(mock.node())
            jobs = [simple_job(count=4) for _ in range(12)]
            eval_ids = [srv.job_register(j)[0] for j in jobs]
            assert wait_for(lambda: all(
                (e := srv.state.eval_by_id(eid)) is not None
                and e.Status == EvalStatusComplete for eid in eval_ids))
            for job in jobs:
                allocs = [a for a in srv.state.allocs_by_job(job.ID)
                          if not a.terminal_status()]
                assert len(allocs) == 4, job.ID
            # The fast path actually ran (not everything fell back).
            stats = srv.workers[0].stats
            assert stats["fast"] > 0
        finally:
            srv.shutdown()

    def test_no_oversubscription_after_burst(self):
        """Optimistic chaining must never commit more than a node's capacity
        (the plan applier re-verifies every placement)."""
        srv = make_server()
        try:
            nodes = []
            for _ in range(4):
                n = mock.node()
                nodes.append(n)
                srv.node_register(n)
            # Enough demand to pack nodes near-full: 4 nodes x 4000 cpu,
            # each alloc asks 500 cpu -> exactly 32 fit.
            jobs = [simple_job(count=4, cpu=500, mem=256)
                    for _ in range(10)]
            eval_ids = [srv.job_register(j)[0] for j in jobs]
            assert wait_for(lambda: all(
                srv.state.eval_by_id(eid) is not None
                and srv.state.eval_by_id(eid).Status not in ("pending",)
                for eid in eval_ids), timeout=20)
            usage = total_usage_by_node(srv.state)
            caps = {n.ID: resources_vec(n.Resources) for n in nodes}
            for node_id, used in usage.items():
                assert np.all(used <= caps[node_id] + 1e-6), (
                    f"node {node_id} oversubscribed: {used} > {caps[node_id]}")
        finally:
            srv.shutdown()

    def test_exhaustion_creates_blocked_eval_via_fast_path(self):
        srv = make_server()
        try:
            n = mock.node()
            n.Resources.CPU = 1000
            srv.node_register(n)
            job = simple_job(count=6, cpu=500)  # 6 x 500 cpu > 1000 cpu
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: (
                (e := srv.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete))
            ev = srv.state.eval_by_id(eval_id)
            assert ev.FailedTGAllocs, "exhaustion must be recorded"
            assert ev.BlockedEval, "a blocked eval must be spawned"
            blocked = srv.state.eval_by_id(ev.BlockedEval)
            assert blocked is not None
            # Capacity arrives: the blocked eval unblocks and places the rest.
            n2 = mock.node()
            srv.node_register(n2)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()]) == 6, timeout=20)
        finally:
            srv.shutdown()

    def test_update_takes_slow_path_and_still_works(self):
        """A job update (destructive) is not pure placement: it must route
        through the per-eval GenericScheduler and still converge."""
        srv = make_server()
        try:
            for _ in range(3):
                srv.node_register(mock.node())
            job = simple_job(count=3)
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()]) == 3)
            # Destructive update: change the task command.
            job2 = job.copy()
            job2.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
            srv.job_register(job2)
            assert wait_for(lambda: srv.workers[0].stats["slow"] > 0,
                            timeout=20)
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()
                and a.Job is None or True]) >= 3, timeout=20)
        finally:
            srv.shutdown()

    def test_parity_with_per_eval_worker(self):
        """Same workload through pipelined and per-eval servers lands the
        same number of allocations with the same per-job placement counts."""
        results = {}
        for pipelined in (True, False):
            srv = Server(ServerConfig(num_schedulers=1,
                                      pipelined_scheduling=pipelined))
            srv.establish_leadership()
            try:
                for i in range(8):
                    srv.node_register(mock.node())
                placed = {}
                eval_ids = []
                jobs = []
                for _ in range(6):
                    job = simple_job(count=5)
                    jobs.append(job)
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(lambda: all(
                    (e := srv.state.eval_by_id(eid)) is not None
                    and e.Status == EvalStatusComplete
                    for eid in eval_ids), timeout=20)
                for job in jobs:
                    placed[job.ID] = len([
                        a for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status()])
                results[pipelined] = sorted(placed.values())
            finally:
                srv.shutdown()
        assert results[True] == results[False] == [5] * 6


class TestChainRebase:
    def test_row_identity_change_rebases_chain(self):
        """A freed row reused by a new node mid-storm must invalidate the
        device usage chain: shape alone doesn't change on free-list reuse,
        so the worker tracks the table's row_epoch."""
        srv = make_server()
        try:
            nodes = [mock.node() for _ in range(4)]
            for n in nodes:
                srv.node_register(n)
            w = srv.workers[0]
            nt = srv.tindex.nt

            # Simulate a live chain built against the current table.
            chain = np.zeros((nt.n_rows, 5), dtype=np.float32)
            w._chain = chain
            w._chain_epoch = nt.row_epoch
            w._chained_windows = 1
            w._drained.clear()  # pipeline "in flight": chain would be kept
            assert w._usage_chain(nt) is not None

            # Node leaves; its row goes to the free list (no resize).
            nt.remove_node(nodes[0].ID)
            w._chain = chain
            assert w._usage_chain(nt) is None, (
                "chain must rebase after a row identity change")
        finally:
            srv.shutdown()


class TestStalePhantomUsage:
    """A record that goes stale/fallback mid-window leaves its chained
    kernel placements as PHANTOM usage: later evals of the window were
    squeezed by capacity that never commits. The worker must re-run those
    evals on the exact path (not park them as blocked evals that no
    capacity event will ever unblock) and rebase the next window's chain.
    (VERDICT r3 weak #4 / ADVICE r2 #3.)"""

    def test_redelivered_eval_does_not_phantom_block_the_window(self):
        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.structs.structs import EvalStatusBlocked

        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=16))
        srv.establish_leadership()
        try:
            node = mock.node()
            node.Resources.CPU = 1000
            node.Resources.MemoryMB = 4000
            node.Reserved = None
            srv.node_register(node)

            # Two jobs that cannot BOTH fit: the chained window has B see
            # A's (ultimately phantom) 600cpu placement.
            job_a = simple_job(count=1, cpu=600, mem=100)
            job_b = simple_job(count=1, cpu=600, mem=100)
            eval_a, _, _ = srv.job_register(job_a)
            eval_b, _, _ = srv.job_register(job_b)

            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=16)
            batch = w._dequeue_window()
            assert {ev.ID for ev, _ in batch} == {eval_a, eval_b}
            # Deterministic chain order: A first, then B.
            batch.sort(key=lambda p: 0 if p[0].ID == eval_a else 1)
            work = w._dispatch_window(batch)
            assert work is not None and len(work.fast) == 2

            # Redeliver A between dispatch and build (nack-timeout shape):
            # its token is no longer outstanding, so the build stage must
            # mark it stale at plan-enqueue.
            rec_a = work.fast[0]
            srv.eval_broker.nack(rec_a.ev.ID, rec_a.token)

            work.packed = w._drain_window(work)
            w._finish_fast(work)

            # A was abandoned (stale), not acked, not planned.
            assert rec_a.stale
            assert w.stats.get("stale", 0) == 1
            # B must NOT be parked as a blocked eval on phantom usage: the
            # node really has 1000 cpu free, so the exact-path re-run
            # places it.
            e_b = srv.state.eval_by_id(eval_b)
            assert e_b is not None and e_b.Status == EvalStatusComplete
            allocs_b = [a for a in srv.state.allocs_by_job(job_b.ID)
                        if not a.terminal_status()]
            assert len(allocs_b) == 1
            assert not [e for e in srv.state.evals_by_job(job_b.ID)
                        if e.Status == EvalStatusBlocked]
            # The next window must rebase off committed state instead of
            # inheriting A's phantom usage.
            assert w._chain_dirty
            assert w._usage_chain(srv.tindex.nt) is None
        finally:
            srv.shutdown()

    def test_inflight_window_detects_taint_from_earlier_window(self):
        """Pipelining keeps windows in flight: window 2 dispatches chained
        on window 1's device tail BEFORE window 1's build discovers its
        record went stale. Window 2 must detect the taint at finish time
        (taint sequence) and re-run its squeezed evals instead of parking
        them blocked."""
        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.structs.structs import EvalStatusBlocked

        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=16))
        srv.establish_leadership()
        try:
            node = mock.node()
            node.Resources.CPU = 1000
            node.Resources.MemoryMB = 4000
            node.Reserved = None
            srv.node_register(node)

            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=16)

            job_a = simple_job(count=1, cpu=600, mem=100)
            eval_a, _, _ = srv.job_register(job_a)
            batch1 = w._dequeue_window()
            work1 = w._dispatch_window(batch1)
            assert work1 is not None and len(work1.fast) == 1
            with w._pending_lock:   # what run() does per dispatched window
                w._pending_windows += 1
                w._drained.clear()

            # Window 2 dispatches on window 1's (soon-phantom) tail.
            job_b = simple_job(count=1, cpu=600, mem=100)
            eval_b, _, _ = srv.job_register(job_b)
            batch2 = w._dequeue_window()
            work2 = w._dispatch_window(batch2)
            assert work2 is not None and len(work2.fast) == 1
            assert work2.chained
            with w._pending_lock:
                w._pending_windows += 1

            # Window 1's record goes stale (redelivered) before its build.
            rec_a = work1.fast[0]
            srv.eval_broker.nack(rec_a.ev.ID, rec_a.token)
            work1.packed = w._drain_window(work1)
            w._finish_fast(work1)
            assert rec_a.stale

            # Window 2 finishes AFTER the taint: its squeezed eval re-runs
            # on the exact path and places for real.
            work2.packed = w._drain_window(work2)
            w._finish_fast(work2)
            e_b = srv.state.eval_by_id(eval_b)
            assert e_b is not None and e_b.Status == EvalStatusComplete
            assert len([a for a in srv.state.allocs_by_job(job_b.ID)
                        if not a.terminal_status()]) == 1
            assert not [e for e in srv.state.evals_by_job(job_b.ID)
                        if e.Status == EvalStatusBlocked]
        finally:
            srv.shutdown()


class TestFastSlowEquivalence:
    """A fixed-seed window run through _finish_fast must commit the same
    placements (node, scores, ports) as the same evals run through
    _process_slow — the fast path only accelerates evals whose outcome is
    provably identical. One record is force-failed at plan commit
    (plan.apply.commit failpoint) so the fallback/phantom-taint re-run is
    part of the compared window, not a separate test."""

    def _fleet(self, n=6):
        return [mock.node() for _ in range(n)]

    def _jobs(self):
        from nomad_tpu.structs import NetworkResource
        from nomad_tpu.structs.structs import Port

        jobs = [simple_job(count=3, cpu=120 + 10 * i, mem=64)
                for i in range(4)]
        # One group WITH a (static, deterministic) port ask: exercises the
        # exact per-placement network path on both sides.
        pj = simple_job(count=1, cpu=80, mem=32)
        task = pj.TaskGroups[0].Tasks[0]
        task.Resources.Networks = [
            NetworkResource(MBits=1,
                            ReservedPorts=[Port("http", 12345)])]
        jobs.append(pj)
        return jobs

    def _placements(self, srv, jobs):
        out = {}
        for job in jobs:
            allocs = sorted(
                (a for a in srv.state.allocs_by_job(job.ID)
                 if not a.terminal_status()), key=lambda a: a.Name)
            out[job.ID] = [
                (a.Name, a.NodeID,
                 round((a.Metrics.Scores or {}).get(
                     f"{a.NodeID}.binpack", 0.0), 4),
                 sorted((p.Label, p.Value)
                        for r in a.TaskResources.values()
                        for net in r.Networks
                        for p in net.ReservedPorts))
                for a in allocs]
        return out

    def test_window_matches_per_eval_path(self, monkeypatch):
        import numpy as np

        from nomad_tpu.resilience import failpoints
        from nomad_tpu.server.pipelined_worker import PipelinedWorker

        # Zero tie-break noise on BOTH paths: placements become a pure
        # function of the (identical) fleet + submission order.
        monkeypatch.setattr(
            "nomad_tpu.scheduler.stack.make_noise_vec",
            lambda n_rows, rng: np.zeros(n_rows, dtype=np.float32))

        fleet = self._fleet()
        jobs = self._jobs()
        # The forced-fallback eval rides its OWN second window: a commit
        # failure re-runs the record AFTER the rest of its window commits,
        # so window membership is what keeps the usage each eval observes
        # identical between the two paths.
        fallback_job = simple_job(count=2, cpu=90, mem=48)
        results = {}
        try:
            for mode in ("fast", "slow"):
                srv = Server(ServerConfig(num_schedulers=0,
                                          pipelined_scheduling=True,
                                          scheduler_window=16))
                srv.establish_leadership()
                try:
                    for node in fleet:
                        srv.node_register(node.copy())
                    for job in jobs:
                        srv.job_register(job.copy())
                    w = PipelinedWorker(
                        srv.raft, srv.eval_broker, srv.plan_queue,
                        srv.blocked_evals, srv.tindex,
                        ["service", "batch", "system"], window=16)
                    batch = w._dequeue_window()
                    assert len(batch) == len(jobs)
                    batch.sort(key=lambda p: p[0].JobID)
                    if mode == "fast":
                        work = w._dispatch_window(batch)
                        assert work is not None
                        assert len(work.fast) == len(jobs)
                        work.packed = w._drain_window(work)
                        w._finish_fast(work)
                        assert w.stats["fast"] == len(jobs)
                    else:
                        for ev, token in batch:
                            w._process_slow(ev, token)

                    # Second window: ONE record whose plan commit is
                    # forced to fail — _finish_fast must re-run it on the
                    # exact path (the phantom-taint machinery raises
                    # _chain_dirty so the next window rebases).
                    srv.job_register(fallback_job.copy())
                    batch2 = w._dequeue_window()
                    assert len(batch2) == 1
                    if mode == "fast":
                        failpoints.arm("plan.apply.commit", "error",
                                       count=1)
                        work2 = w._dispatch_window(batch2)
                        assert work2 is not None and len(work2.fast) == 1
                        work2.packed = w._drain_window(work2)
                        w._finish_fast(work2)
                        assert w.stats["fallback"] == 1, \
                            "the forced-fallback record never re-ran"
                        assert w._chain_dirty, \
                            "fallback must taint the chain for rebase"
                    else:
                        for ev, token in batch2:
                            w._process_slow(ev, token)
                    results[mode] = self._placements(
                        srv, jobs + [fallback_job])
                finally:
                    srv.shutdown()
        finally:
            failpoints.disarm_all()
        assert results["fast"] == results["slow"]
        # Non-vacuous: real scores and the static port came through.
        flat = [t for allocs in results["fast"].values() for t in allocs]
        assert any(score > 0 for _, _, score, _ in flat)
        assert any(ports == [("http", 12345)] for _, _, _, ports in flat)


class TestWindowFusion:
    def test_interleaved_preps_fuse_and_place_correctly(self):
        """A window mixing two job shapes (A,B,A,B...) fuses only
        consecutive shared-prep runs; placements still match totals and
        nothing oversubscribes."""
        srv = Server(ServerConfig(num_schedulers=0,
                                  pipelined_scheduling=True,
                                  scheduler_window=32,
                                  host_placement=False))
        srv.establish_leadership()
        try:
            from nomad_tpu.server.pipelined_worker import PipelinedWorker

            for _ in range(10):
                srv.node_register(mock.node())
            jobs = []
            for i in range(8):
                if i % 2 == 0:
                    job = simple_job(count=2, cpu=100, mem=64)
                else:
                    job = simple_job(count=3, cpu=150, mem=96)
                jobs.append(job)
                srv.job_register(job)
            w = PipelinedWorker(srv.raft, srv.eval_broker, srv.plan_queue,
                                srv.blocked_evals, srv.tindex,
                                ["service", "batch", "system"], window=32,
                                host_placement=False)
            batch = w._dequeue_window()
            assert len(batch) == 8
            work = w._dispatch_window(batch)
            assert work is not None and len(work.fast) == 8
            work.packed = w._drain_window(work)
            w._finish_fast(work)
            for job in jobs:
                want = job.TaskGroups[0].Count
                got = len([a for a in srv.state.allocs_by_job(job.ID)
                           if not a.terminal_status()])
                assert got == want, (job.ID, got, want)
            assert w.stats.get("multi", 0) >= 1  # at least one fused run
        finally:
            srv.shutdown()
