"""Scheduler scenario tests (shaped after reference
scheduler/generic_sched_test.go and system_sched_test.go)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import Constraint, Resources
from nomad_tpu.structs.structs import (
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusBlocked,
    EvalStatusComplete,
    EvalStatusPending,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
    EvalTriggerRollingUpdate,
    JobTypeBatch,
    NodeStatusDown,
)


def make_eval(job, trigger=EvalTriggerJobRegister, status=EvalStatusPending):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = status
    return ev


class TestServiceSched:
    def test_job_register(self):
        """10 nodes, count-10 job: all placed, spread 1/node by anti-affinity
        (reference: TestServiceSched_JobRegister)."""
        h = Harness()
        for _ in range(10):
            h.upsert("node", mock.node())
        job = mock.job()
        h.upsert("job", job)
        ev = make_eval(job)
        h.process("service", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        placed = [a for allocs in plan.NodeAllocation.values() for a in allocs]
        assert len(placed) == 10
        # Anti-affinity spreads across all 10 nodes.
        assert len(plan.NodeAllocation) == 10
        assert h.evals[-1].Status == EvalStatusComplete
        # Names follow job.tg[i] materialization.
        names = {a.Name for a in placed}
        assert names == {f"{job.Name}.web[{i}]" for i in range(10)}
        # Allocs landed in the store.
        assert len(h.state.allocs_by_job(job.ID)) == 10

    def test_no_nodes_blocked_eval(self):
        """No nodes: failed placement creates a blocked eval
        (reference: TestServiceSched_JobRegister_BlockedEval)."""
        h = Harness()
        job = mock.job()
        h.upsert("job", job)
        ev = make_eval(job)
        h.process("service", ev)
        assert len(h.creates) == 1
        blocked = h.creates[0]
        assert blocked.Status == EvalStatusBlocked
        assert blocked.PreviousEval == ev.ID
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        assert final.BlockedEval == blocked.ID
        assert "web" in final.FailedTGAllocs
        # No plan submitted (no-op).
        assert h.plans == []

    def test_exhausted_resources_partial(self):
        """Nodes can hold some but not all instances: partial placement +
        blocked eval with CoalescedFailures."""
        h = Harness()
        node = mock.node()  # 4000 CPU, 8192 MB; reserved 100/256
        h.upsert("node", node)
        job = mock.job()
        # Each instance wants 1500 CPU: only 2 fit ((4000-100) // 1500).
        job.TaskGroups[0].Tasks[0].Resources.CPU = 1500
        job.TaskGroups[0].Count = 5
        h.upsert("job", job)
        ev = make_eval(job)
        h.process("service", ev)
        placed = [a for p in h.plans for allocs in p.NodeAllocation.values()
                  for a in allocs]
        assert len(placed) == 2
        final = h.evals[-1]
        assert final.FailedTGAllocs["web"].CoalescedFailures == 2  # 3 failed: 1 + 2 coalesced
        assert len(h.creates) == 1

    def test_constraint_filters_nodes(self):
        h = Harness()
        good = mock.node()
        h.upsert("node", good)
        bad = mock.node()
        bad.Attributes["kernel.name"] = "windows"
        from nomad_tpu.structs import compute_node_class
        compute_node_class(bad)
        h.upsert("node", bad)
        job = mock.job()  # constraint kernel.name = linux
        job.TaskGroups[0].Count = 2
        h.upsert("job", job)
        h.process("service", make_eval(job))
        placed = [a for p in h.plans for allocs in p.NodeAllocation.values()
                  for a in allocs]
        assert len(placed) == 2
        assert all(a.NodeID == good.ID for a in placed)

    def test_distinct_hosts(self):
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.job()
        job.Constraints.append(Constraint(Operand="distinct_hosts"))
        job.TaskGroups[0].Count = 5
        h.upsert("job", job)
        h.process("service", make_eval(job))
        placed = [a for p in h.plans for allocs in p.NodeAllocation.values()
                  for a in allocs]
        # Only 3 hosts: 3 placed on distinct nodes, 2 fail.
        assert len(placed) == 3
        assert len({a.NodeID for a in placed}) == 3
        assert h.evals[-1].FailedTGAllocs["web"].CoalescedFailures == 1

    def test_drain_migrates(self):
        """Draining node migrates its allocs (reference:
        TestServiceSched_NodeDrain)."""
        h = Harness()
        draining = mock.node()
        draining.Drain = True
        h.upsert("node", draining)
        target = mock.node()
        h.upsert("node", target)
        job = mock.job()
        job.TaskGroups[0].Count = 2
        h.upsert("job", job)
        allocs = []
        for i in range(2):
            a = mock.alloc()
            a.Job = h.state.job_by_id(job.ID)
            a.JobID = job.ID
            a.NodeID = draining.ID
            a.Name = f"{job.Name}.web[{i}]"
            allocs.append(a)
        h.upsert("allocs", allocs)
        ev = make_eval(job, trigger=EvalTriggerNodeUpdate)
        h.process("service", ev)
        plan = h.plans[0]
        stops = [a for allocs in plan.NodeUpdate.values() for a in allocs]
        assert len(stops) == 2
        assert all(a.DesiredStatus == AllocDesiredStatusStop for a in stops)
        placed = [a for allocs in plan.NodeAllocation.values() for a in allocs]
        assert len(placed) == 2
        assert all(a.NodeID == target.ID for a in placed)

    def test_job_deregister_stops_allocs(self):
        """Job removed: all allocs stopped (reference:
        TestServiceSched_JobDeregister)."""
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.job()
        allocs = []
        for i in range(5):
            a = mock.alloc()
            a.Job = job
            a.JobID = job.ID
            a.NodeID = node.ID
            a.Name = f"{job.Name}.web[{i}]"
            allocs.append(a)
        h.upsert("allocs", allocs)
        from nomad_tpu.structs.structs import EvalTriggerJobDeregister
        ev = make_eval(job, trigger=EvalTriggerJobDeregister)
        h.process("service", ev)
        plan = h.plans[0]
        stops = [a for allocs in plan.NodeUpdate.values() for a in allocs]
        assert len(stops) == 5
        assert h.evals[-1].Status == EvalStatusComplete

    def test_inplace_update(self):
        """Job tweak that doesn't change tasks updates in place
        (reference: TestServiceSched_JobModify_InPlace)."""
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 2
        h.upsert("job", job)
        stored_job = h.state.job_by_id(job.ID)
        allocs = []
        for i in range(2):
            a = mock.alloc()
            a.Job = stored_job
            a.JobID = job.ID
            a.NodeID = node.ID
            a.Name = f"{job.Name}.web[{i}]"
            allocs.append(a)
        h.upsert("allocs", allocs)
        # Re-register with a non-task change (priority): JobModifyIndex bumps.
        job2 = stored_job.copy()
        job2.Priority = 60
        h.upsert("job", job2)
        ev = make_eval(job2)
        h.process("service", ev)
        plan = h.plans[0]
        placed = [a for allocs in plan.NodeAllocation.values() for a in allocs]
        assert len(placed) == 2
        # In-place: no evictions in the final plan, same alloc IDs kept.
        stops = [a for allocs in plan.NodeUpdate.values() for a in allocs]
        assert stops == []
        assert {a.ID for a in placed} == {a.ID for a in allocs}

    def test_destructive_update(self):
        """Task config change forces stop + replace
        (reference: TestServiceSched_JobModify)."""
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 2
        h.upsert("job", job)
        stored_job = h.state.job_by_id(job.ID)
        allocs = []
        for i in range(2):
            a = mock.alloc()
            a.Job = stored_job
            a.JobID = job.ID
            a.NodeID = node.ID
            a.Name = f"{job.Name}.web[{i}]"
            allocs.append(a)
        h.upsert("allocs", allocs)
        job2 = stored_job.copy()
        job2.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
        h.upsert("job", job2)
        h.process("service", make_eval(job2))
        plan = h.plans[0]
        stops = [a for allocs in plan.NodeUpdate.values() for a in allocs]
        placed = [a for allocs in plan.NodeAllocation.values() for a in allocs]
        assert len(stops) == 2
        assert len(placed) == 2
        assert {a.ID for a in placed}.isdisjoint({a.ID for a in stops})

    def test_rolling_update_limit(self):
        """MaxParallel caps destructive updates per pass and spawns a
        follow-up eval (reference: TestServiceSched_JobModify_Rolling)."""
        h = Harness()
        node = mock.node()
        node.Resources = Resources(CPU=40000, MemoryMB=81920, DiskMB=1024*1024,
                                   IOPS=5000,
                                   Networks=node.Resources.Networks)
        h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 10
        h.upsert("job", job)
        stored_job = h.state.job_by_id(job.ID)
        allocs = []
        for i in range(10):
            a = mock.alloc()
            a.Job = stored_job
            a.JobID = job.ID
            a.NodeID = node.ID
            a.Name = f"{job.Name}.web[{i}]"
            allocs.append(a)
        h.upsert("allocs", allocs)
        job2 = stored_job.copy()
        job2.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
        job2.Update.Stagger = 30 * 10**9
        job2.Update.MaxParallel = 3
        h.upsert("job", job2)
        h.process("service", make_eval(job2))
        plan = h.plans[0]
        stops = [a for allocs in plan.NodeUpdate.values() for a in allocs]
        assert len(stops) == 3
        # Follow-up rolling eval created.
        rolling = [e for e in h.creates
                   if e.TriggeredBy == EvalTriggerRollingUpdate]
        assert len(rolling) == 1
        assert rolling[0].Wait == 30 * 10**9

    def test_batch_ignores_complete(self):
        """Batch allocs that ran successfully are not replaced
        (reference: TestGenericSched_FilterCompleteAllocs)."""
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.job()
        job.Type = JobTypeBatch
        job.TaskGroups[0].Count = 1
        h.upsert("job", job)
        stored_job = h.state.job_by_id(job.ID)
        from nomad_tpu.structs import TaskState, TaskEvent
        from nomad_tpu.structs.structs import (
            AllocClientStatusComplete, TaskStateDead, TaskTerminated)
        a = mock.alloc()
        a.Job = stored_job
        a.JobID = job.ID
        a.NodeID = node.ID
        a.Name = f"{job.Name}.web[0]"
        a.ClientStatus = AllocClientStatusComplete
        a.TaskStates = {"web": TaskState(
            State=TaskStateDead,
            Events=[TaskEvent(Type=TaskTerminated, ExitCode=0)])}
        h.upsert("allocs", [a])
        h.process("batch", make_eval(job))
        # Nothing to do: the work already finished.
        placed = [x for p in h.plans for allocs in p.NodeAllocation.values()
                  for x in allocs]
        assert placed == []
        assert h.evals[-1].Status == EvalStatusComplete

    def test_annotate_plan(self):
        h = Harness()
        h.upsert("node", mock.node())
        job = mock.job()
        job.TaskGroups[0].Count = 3
        h.upsert("job", job)
        ev = make_eval(job)
        ev.AnnotatePlan = True
        h.process("service", ev)
        plan = h.plans[0]
        assert plan.Annotations is not None
        des = plan.Annotations.DesiredTGUpdates["web"]
        assert des.Place == 3

    def test_plan_rejection_retries_then_blocks(self):
        """Rejected plans exhaust attempts -> failed status + blocked eval
        (reference: testing.go RejectPlan usage)."""
        h = Harness()
        h.upsert("node", mock.node())
        job = mock.job()
        job.TaskGroups[0].Count = 1
        h.upsert("job", job)
        h.reject_plan = True
        h.process("service", make_eval(job))
        final = h.evals[-1]
        assert final.Status == "failed"
        assert any(e.TriggeredBy == "max-plan-attempts" for e in h.creates)


class TestSystemSched:
    def test_register_places_on_all_nodes(self):
        """(reference: TestSystemSched_JobRegister)"""
        h = Harness()
        nodes = [mock.node() for _ in range(10)]
        for n in nodes:
            h.upsert("node", n)
        job = mock.system_job()
        h.upsert("job", job)
        ev = make_eval(job)
        h.process("system", ev)
        plan = h.plans[0]
        placed = [a for allocs in plan.NodeAllocation.values() for a in allocs]
        assert len(placed) == 10
        assert {a.NodeID for a in placed} == {n.ID for n in nodes}
        assert h.evals[-1].Status == EvalStatusComplete

    def test_down_node_stops_alloc(self):
        """(reference: TestSystemSched_NodeDown)"""
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.system_job()
        h.upsert("job", job)
        stored_job = h.state.job_by_id(job.ID)
        a = mock.alloc()
        a.Job = stored_job
        a.JobID = job.ID
        a.NodeID = node.ID
        a.Name = f"{job.Name}.web[0]"
        h.upsert("allocs", [a])
        h.state.update_node_status(h._next_index(), node.ID, NodeStatusDown)
        ev = make_eval(job, trigger=EvalTriggerNodeUpdate)
        h.process("system", ev)
        plan = h.plans[0]
        stops = [x for allocs in plan.NodeUpdate.values() for x in allocs]
        assert len(stops) == 1
        assert stops[0].ID == a.ID

    def test_constraints_respected(self):
        h = Harness()
        good = mock.node()
        h.upsert("node", good)
        bad = mock.node()
        bad.Attributes["kernel.name"] = "darwin"
        from nomad_tpu.structs import compute_node_class
        compute_node_class(bad)
        h.upsert("node", bad)
        job = mock.system_job()
        h.upsert("job", job)
        h.process("system", make_eval(job))
        placed = [a for p in h.plans for allocs in p.NodeAllocation.values()
                  for a in allocs]
        assert len(placed) == 1
        assert placed[0].NodeID == good.ID
