"""Overlapped plan applier + evaluate pool (reference: nomad/plan_apply.go
planApply's optimistic-snapshot overlap and plan_apply_pool.go's per-node
verification fan-out)."""

import threading
import time

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import FSM, DevRaft, MessageType
from nomad_tpu.server.plan_apply import OptimisticSnapshot, PlanApplier, evaluate_plan
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.structs import Plan
from nomad_tpu.tensor.node_table import alloc_vec


class SlowRaft:
    """DevRaft wrapper that makes every apply pay a consensus-like latency,
    so the verify/apply overlap is measurable."""

    def __init__(self, fsm, delay=0.01):
        self._inner = DevRaft(fsm)
        self.fsm = fsm
        self.delay = delay

    def apply(self, msg_type, payload):
        time.sleep(self.delay)
        return self._inner.apply(msg_type, payload)

    @property
    def last_index(self):
        return self._inner.last_index


def _register_nodes(raft, n, cpu=4000):
    nodes = []
    for _ in range(n):
        node = mock.node()
        node.Resources.CPU = cpu
        node.Reserved = None  # capacity arithmetic in tests assumes none
        raft.apply(MessageType.NodeRegister, {"Node": node})
        nodes.append(node)
    return nodes


def _make_plan(nodes, cpu_per_alloc=100, allocs_per_node=1):
    plan = Plan(EvalID=f"eval-{id(nodes)}-{time.monotonic_ns()}", Priority=50)
    for node in nodes:
        placed = []
        for _ in range(allocs_per_node):
            alloc = mock.alloc()
            alloc.NodeID = node.ID
            alloc.Resources.CPU = cpu_per_alloc
            alloc.Resources.Networks = []
            alloc.TaskResources = {}
            placed.append(alloc)
        plan.NodeAllocation[node.ID] = placed
    return plan


class TestOptimisticSnapshot:
    def test_overlay_adds_and_removes(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        [node] = _register_nodes(raft, 1)
        base = mock.alloc()
        base.NodeID = node.ID
        raft.apply(MessageType.AllocUpdate, {"Alloc": [base],
                                             "Job": base.Job})
        opt = OptimisticSnapshot(fsm.state.snapshot())
        assert len(opt.allocs_by_node_terminal(node.ID, False)) == 1

        from nomad_tpu.structs import PlanResult
        new = mock.alloc()
        new.NodeID = node.ID
        result = PlanResult(NodeAllocation={node.ID: [new]},
                            NodeUpdate={node.ID: [base]})
        opt.apply_result(result)
        live = opt.allocs_by_node_terminal(node.ID, False)
        assert [a.ID for a in live] == [new.ID]

    def test_second_plan_sees_first_plans_usage(self):
        """The core overlap-safety property: plan N+1 verified against the
        optimistic view cannot oversubscribe what plan N consumed."""
        fsm = FSM()
        raft = DevRaft(fsm)
        [node] = _register_nodes(raft, 1, cpu=1000)
        opt = OptimisticSnapshot(fsm.state.snapshot())

        plan1 = _make_plan([node], cpu_per_alloc=600)
        r1 = evaluate_plan(opt, plan1)
        assert r1.NodeAllocation  # fits
        opt.apply_result(r1)

        plan2 = _make_plan([node], cpu_per_alloc=600)
        r2 = evaluate_plan(opt, plan2)
        assert not r2.NodeAllocation  # 600+600 > 1000: must be refused
        assert r2.RefreshIndex > 0


class TestVectorFitTornReads:
    def test_no_admission_from_torn_row_reads(self):
        """_vector_fit must snapshot rows under the tensor lock: alloc
        commits mutate usage rows in place, and an unlocked reader could see
        half of one `+=` and half of another. Constructed so every LEGAL
        point-in-time state rejects the placement — only a torn mix of two
        writes (e.g. [0, 0]) could admit it."""
        from nomad_tpu.server.plan_apply import _vector_fit
        from nomad_tpu.structs import Resources
        from nomad_tpu.tensor.node_table import NodeTensor

        node = mock.node()
        node.Resources = Resources(CPU=50, MemoryMB=50)
        node.Reserved = None
        nt = NodeTensor()
        nt.upsert_node(node)

        def usage_alloc(cpu=0, mem=0):
            a = mock.alloc()
            a.NodeID = node.ID
            a.Resources = Resources(CPU=cpu, MemoryMB=mem)
            a.TaskResources = {}
            return a

        alloc_a = usage_alloc(cpu=100)   # [100, 0, ...]
        alloc_b = usage_alloc(mem=100)   # [0, 100, ...]
        nt.add_alloc_usage(alloc_a)      # states cycle A, A+B, B — all of
        # which exceed capacity in SOME dim; [0, 0] is reachable only torn.

        class Snap:
            row_delta = {}

            @staticmethod
            def node_by_id(_):
                return node

            @staticmethod
            def alloc_by_id(_):
                return None

        ask = usage_alloc(cpu=1, mem=1)
        plan = Plan(EvalID="torn", NodeAllocation={node.ID: [ask]})

        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                nt.add_alloc_usage(alloc_b)      # A   -> A+B
                nt.remove_alloc_usage(alloc_a)   # A+B -> B
                nt.add_alloc_usage(alloc_a)      # B   -> A+B
                nt.remove_alloc_usage(alloc_b)   # A+B -> A

        writer = threading.Thread(target=hammer, daemon=True)
        writer.start()
        try:
            for _ in range(3000):
                fits, exact = _vector_fit(Snap, plan, nt, [node.ID])
                assert exact == []
                assert fits[node.ID] is False, \
                    "torn row read admitted an impossible placement"
        finally:
            stop.set()
            writer.join(timeout=5)


class TestContentionStorm:
    def test_no_oversubscription_under_many_conflicting_plans(self):
        """Many concurrent workers submit plans fighting over a small node
        set; committed state never exceeds capacity."""
        fsm = FSM()
        raft = DevRaft(fsm)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)  # no broker: skip token check
        applier.start()
        try:
            nodes = _register_nodes(raft, 4, cpu=2000)
            results = []
            lock = threading.Lock()

            def worker(i):
                for _ in range(6):
                    plan = _make_plan(nodes, cpu_per_alloc=400)
                    pending = queue.enqueue(plan)
                    res = pending.wait(timeout=10)
                    with lock:
                        results.append(res)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            assert len(results) == 36
            # Committed usage per node never exceeds capacity.
            for node in nodes:
                used = sum(
                    alloc_vec(a)[0]
                    for a in fsm.state.allocs_by_node(node.ID)
                    if not a.terminal_status())
                assert used <= 2000, f"node oversubscribed: {used}"
            # 4 nodes x 2000cpu / 400cpu = 20 allocs max; every commit is real.
            total = sum(1 for a in fsm.state.allocs()
                        if not a.terminal_status())
            assert total == 20
        finally:
            applier.stop()
            queue.set_enabled(False)

    def test_verify_runs_while_apply_in_flight(self):
        """The overlap property asserted STRUCTURALLY: while the first
        group's consensus apply is parked (gated on an event), the
        applier must verify the next plans against the optimistic view —
        observable as `overlapped` counts recorded before the apply is
        released. The old form of this test timed serial vs overlapped
        wall clock, which traded places with machine load; gating on
        events makes the property deterministic."""
        from helpers import wait_for

        fsm = FSM()
        in_flight = threading.Event()
        release = threading.Event()

        class GatedRaft:
            """First apply parks mid-consensus until released."""

            def __init__(self, fsm):
                self._inner = DevRaft(fsm)
                self.fsm = fsm
                self.applies = 0

            def apply(self, msg_type, payload):
                self.applies += 1
                if self.applies == 1:
                    in_flight.set()
                    assert release.wait(20), "test released the gate late"
                return self._inner.apply(msg_type, payload)

            @property
            def last_index(self):
                return self._inner.last_index

        raft = GatedRaft(fsm)
        nodes = _register_nodes(raft._inner, 16, cpu=100000)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft, pool_size=2)
        applier.start()
        try:
            first = queue.enqueue(_make_plan(nodes, 10))
            assert in_flight.wait(20)  # apply #1 parked mid-consensus
            # One ATOMIC window (what workers do): per-plan enqueues let
            # the applier wake between them, verify a 1-plan group, and
            # park joining apply #1 with overlapped stuck below 3 — the
            # last wall-clock-scheduling dependence this test had.
            laters = queue.enqueue_all([_make_plan(nodes, 10)
                                        for _ in range(3)])
            # The overlap: with apply #1 still in flight, the next group
            # verifies against the optimistic snapshot.
            assert wait_for(lambda: applier.stats["overlapped"] >= 3,
                            timeout=20,
                            msg="later plans verified during the apply")
            assert applier.stats["applied"] == 0  # nothing committed yet
            release.set()
            results = [p.wait(timeout=20) for p in [first] + laters]
            assert all(r is not None and r.NodeAllocation
                       for r in results)
            assert applier.stats["applied"] == 4
            total = sum(1 for a in fsm.state.allocs()
                        if not a.terminal_status())
            assert total == 4 * len(nodes)
        finally:
            release.set()
            applier.stop()
            queue.set_enabled(False)
            applier.join(timeout=30)

    def test_overlapped_counter_advances(self):
        fsm = FSM()
        raft = SlowRaft(fsm, delay=0.02)
        nodes = _register_nodes(raft._inner, 12, cpu=100000)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft, pool_size=2)
        applier.start()
        try:
            pendings = [queue.enqueue(_make_plan(nodes, 10))
                        for _ in range(8)]
            for p in pendings:
                assert p.wait(timeout=10) is not None
            assert applier.stats["applied"] == 8
            assert applier.stats["overlapped"] > 0
        finally:
            applier.stop()
            queue.set_enabled(False)


class TestGroupedCommit:
    def test_queued_plans_commit_as_groups(self):
        """Plans enqueued back-to-back (a worker window) verify against the
        chained overlay and land as grouped consensus entries: every plan
        fully commits, capacity is respected, and the entry count is well
        below one-per-plan."""
        fsm = FSM()
        raft = SlowRaft(fsm, delay=0.02)  # applies slow: queue builds up
        nodes = _register_nodes(raft._inner, 8, cpu=100000)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft, pool_size=2)
        applier.start()
        try:
            pendings = [queue.enqueue(_make_plan(nodes, 10))
                        for _ in range(24)]
            results = [p.wait(timeout=20) for p in pendings]
            assert all(r is not None for r in results)
            # Every plan fully committed (no conflicts: huge capacity).
            for r in results:
                assert r.NodeAllocation
            assert applier.stats["applied"] == 24
            total = sum(1 for a in fsm.state.allocs()
                        if not a.terminal_status())
            assert total == 24 * len(nodes)
            # Grouping happened: strictly fewer consensus entries than plans
            # (each SlowRaft apply pays 20ms; 24 serial applies would take
            # ~480ms of apply latency alone while the queue refills).
            distinct_indexes = {r.AllocIndex for r in results}
            assert len(distinct_indexes) < 24, distinct_indexes
        finally:
            applier.stop()
            queue.set_enabled(False)

    def test_grouped_plans_respect_capacity(self):
        """Conflicting plans in one group chain through the shared overlay:
        later plans in the group see earlier group members' usage, so a
        group can never jointly oversubscribe a node."""
        fsm = FSM()
        raft = SlowRaft(fsm, delay=0.02)
        nodes = _register_nodes(raft._inner, 2, cpu=1000)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft, pool_size=2)
        applier.start()
        try:
            # 8 plans x 2 nodes x 400cpu: only 2 fit per node.
            pendings = [queue.enqueue(_make_plan(nodes, cpu_per_alloc=400))
                        for _ in range(8)]
            for p in pendings:
                assert p.wait(timeout=20) is not None
            for node in nodes:
                used = sum(alloc_vec(a)[0]
                           for a in fsm.state.allocs_by_node(node.ID)
                           if not a.terminal_status())
                assert used <= 1000, f"node oversubscribed: {used}"
            total = sum(1 for a in fsm.state.allocs()
                        if not a.terminal_status())
            assert total == 4
        finally:
            applier.stop()
            queue.set_enabled(False)


class TestTwoSubmitterWindows:
    """Two pipelined workers submit whole WINDOWS of plans concurrently
    (PlanQueue.enqueue_all) while applies are in flight (SlowRaft): the
    applier's verify/apply overlap must stay correct with N submitters —
    every future answered, each window contiguous in the queue, committed
    state never over capacity, and the capacity-limited total exact (the
    optimistic overlay cannot double-admit across two workers' chains)."""

    def test_concurrent_window_submits_stay_correct(self):
        fsm = FSM()
        raft = SlowRaft(fsm, delay=0.004)  # applies overlap verifies
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)  # no broker: skip token check
        applier.start()
        try:
            nodes = _register_nodes(raft, 4, cpu=2000)
            results = []
            lock = threading.Lock()

            def submitter(i):
                for _ in range(4):
                    window = [_make_plan(nodes, cpu_per_alloc=400)
                              for _ in range(3)]
                    pendings = queue.enqueue_all(window)
                    for pending in pendings:
                        res = pending.wait(timeout=10)
                        with lock:
                            results.append(res)

            threads = [threading.Thread(target=submitter, args=(i,),
                                        name=f"submitter-{i}")
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            assert len(results) == 24
            assert all(r is not None for r in results)
            for node in nodes:
                used = sum(
                    alloc_vec(a)[0]
                    for a in fsm.state.allocs_by_node(node.ID)
                    if not a.terminal_status())
                assert used <= 2000, f"node oversubscribed: {used}"
            # 4 nodes x 2000cpu / 400cpu = 20 allocs max; every admitted
            # placement is real and nothing double-committed.
            total = sum(1 for a in fsm.state.allocs()
                        if not a.terminal_status())
            assert total == 20
        finally:
            applier.stop()
            queue.set_enabled(False)


class TestLeadershipFlap:
    def test_flap_never_revives_or_orphans_an_applier(self):
        """stop();start() in quick succession (leadership flap) must leave
        exactly ONE live applier: per-run stop events mean the old run
        cannot be revived by a cleared flag, the new run serializes behind
        it, and join() reaps retired runs."""
        fsm = FSM()
        raft = SlowRaft(fsm, delay=0.02)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        applier.start()
        nodes = _register_nodes(raft, 4, cpu=2000)
        try:
            # Keep plans flowing across the flaps.
            stop_feed = threading.Event()
            def feeder():
                while not stop_feed.is_set():
                    pending = queue.enqueue(_make_plan(nodes,
                                                       cpu_per_alloc=100))
                    pending.wait(timeout=10)
            feeders = [threading.Thread(target=feeder) for _ in range(2)]
            for t in feeders:
                t.start()
            for _ in range(5):  # rapid flaps
                applier.stop()
                applier.start()
                time.sleep(0.05)
            stop_feed.set()
            for t in feeders:
                t.join(timeout=20)
            deadline = time.time() + 10
            def live():
                return [t for t in threading.enumerate()
                        if t.name == "plan-apply" and t.is_alive()]
            while time.time() < deadline and len(live()) > 1:
                time.sleep(0.05)
            assert len(live()) == 1, [t.name for t in live()]
            # The survivor still commits plans.
            pending = queue.enqueue(_make_plan(nodes, cpu_per_alloc=100))
            res = pending.wait(timeout=10)
            assert res is not None
            # No oversubscription slipped through the flap windows.
            for node in nodes:
                used = sum(alloc_vec(a)[0]
                           for a in fsm.state.allocs_by_node(node.ID)
                           if not a.terminal_status())
                assert used <= 2000, f"node oversubscribed: {used}"
        finally:
            applier.stop()
            queue.set_enabled(False)
            applier.join(timeout=30)
            assert not [t for t in threading.enumerate()
                        if t.name == "plan-apply" and t.is_alive()]
