"""Per-task resource usage sampling + the alloc stats API
(reference: client/driver/executor/executor.go:36-41, /v1/client/allocation/
<id>/stats)."""

import pytest

import os
import subprocess
import time

from nomad_tpu.client.stats import TaskStatsTracker, sample_pid_tree


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # real timers/sockets: one retry

class TestPidTreeSampling:
    def test_samples_own_process_group(self):
        # Spawn a process group: a shell with a sleeping child.
        proc = subprocess.Popen(
            ["/bin/sh", "-c", "sleep 30 & sleep 30"],
            preexec_fn=os.setsid)
        try:
            assert wait_for(
                lambda: len(sample_pid_tree(proc.pid)[0]) >= 2)
            pids, user, system, rss = sample_pid_tree(proc.pid)
            assert proc.pid in pids
            assert rss > 0
            assert user >= 0.0 and system >= 0.0
        finally:
            os.killpg(proc.pid, 15)
            proc.wait()

    def test_unknown_group_is_empty(self):
        pids, user, system, rss = sample_pid_tree(2**22 - 1)
        assert pids == [] and rss == 0


class TestTracker:
    def test_cpu_percent_from_deltas(self):
        tracker = TaskStatsTracker()
        first = tracker.usage("k", {"pids": [1], "user_seconds": 1.0,
                                    "system_seconds": 0.5,
                                    "rss_bytes": 4096})
        assert first["ResourceUsage"]["CpuStats"]["Percent"] == 0.0
        time.sleep(0.05)
        second = tracker.usage("k", {"pids": [1], "user_seconds": 1.2,
                                     "system_seconds": 0.6,
                                     "rss_bytes": 8192})
        assert second["ResourceUsage"]["CpuStats"]["Percent"] > 0
        assert second["ResourceUsage"]["MemoryStats"]["RSS"] == 8192

    def test_docker_style_percent_passthrough(self):
        tracker = TaskStatsTracker()
        u = tracker.usage("d", {"cpu_percent": 12.5, "rss_bytes": 1024})
        assert u["ResourceUsage"]["CpuStats"]["Percent"] == 12.5

    def test_none_sample(self):
        assert TaskStatsTracker().usage("x", None) is None


class TestDockerMemParsing:
    def test_units_longest_suffix_first(self):
        from nomad_tpu.client.driver.docker import _parse_mem

        assert _parse_mem("5.3MiB") == int(5.3 * 2**20)
        assert _parse_mem("1.5GiB") == int(1.5 * 2**30)
        assert _parse_mem("200KiB") == 200 * 1024
        assert _parse_mem("7MB") == 7 * 1000**2
        assert _parse_mem("123B") == 123
        assert _parse_mem("42") == 42


class TestAllocStatsE2E:
    def test_stats_through_http(self, tmp_path):
        from nomad_tpu import mock
        from nomad_tpu.agent import Agent, AgentConfig
        from nomad_tpu.api import Client as ApiClient

        conf = AgentConfig.dev()
        conf.http_port = 0
        conf.data_dir = str(tmp_path)
        agent = Agent(conf)
        agent.start()
        try:
            api = ApiClient(f"http://127.0.0.1:{agent.http.port}")
            job = mock.job()
            job.ID = job.Name = "stats-job"
            tg = job.TaskGroups[0]
            tg.Count = 1
            task = tg.Tasks[0]
            task.Driver = "raw_exec"
            task.Config = {"command": "/bin/sleep", "args": ["300"]}
            task.Services = []
            job.init_fields()
            api.jobs.register(job)

            def running_alloc():
                allocs, _ = api.allocations.list()
                for a in allocs:
                    if a["ClientStatus"] == "running":
                        return a["ID"]
                return None
            assert wait_for(running_alloc, timeout=30)
            alloc_id = running_alloc()

            def live_stats():
                stats, _ = api.allocations.stats(alloc_id)
                return stats if stats.get("Tasks") else None
            assert wait_for(live_stats, timeout=15)
            stats = live_stats()
            usage = stats["Tasks"][task.Name]["ResourceUsage"]
            assert usage["MemoryStats"]["RSS"] > 0
            assert stats["ResourceUsage"]["MemoryStats"]["RSS"] > 0
            assert stats["Tasks"][task.Name]["Pids"]
        finally:
            agent.shutdown()
