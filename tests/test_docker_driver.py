"""Docker driver lifecycle against the stub daemon CLI (fake_docker.py).

The reference's docker suite (client/driver/docker_test.go) gates on a
live daemon; the stub lets start -> log pump -> stats -> wait/kill ->
cleanup run unconditionally, and additionally asserts the daemon
endpoint/TLS options and registry auth reach the CLI invocations.
"""

import json
import os
import stat
import sys
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.driver import new_driver
from nomad_tpu.client.driver.base import DriverContext, ExecContext
from nomad_tpu.client.env import TaskEnv

from helpers import wait_for  # noqa: E402

# Every assertion here rides real subprocess round-trips (the docker shim
# is a python interpreter start per CLI call); on a loaded suite run a
# single invocation can stall past any fixed margin. Same opt-in retry as
# the cluster/chaos suites.
pytestmark = pytest.mark.timing_retry


@pytest.fixture
def fake_docker(tmp_path, monkeypatch):
    """Install the stub `docker` on PATH; returns the state dir."""
    bin_dir = tmp_path / "bin"
    state = tmp_path / "docker-state"
    bin_dir.mkdir()
    state.mkdir()
    shim = bin_dir / "docker"
    fake = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fake_docker.py")
    # -S -E: skip site/sitecustomize (the TPU plugin alone costs ~2s of
    # interpreter startup per CLI invocation on this host).
    shim.write_text(f"#!/bin/sh\nexec {sys.executable} -S -E {fake} \"$@\"\n")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_DOCKER_STATE", str(state))
    return state


def _invocations(state):
    path = state / "invocations.jsonl"
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


def _task(image, command="", args=(), config=None):
    alloc = mock.alloc()
    task = alloc.Job.TaskGroups[0].Tasks[0]
    task.Driver = "docker"
    task.Config = {"image": image}
    if command:
        task.Config["command"] = command
        task.Config["args"] = list(args)
    task.Config.update(config or {})
    task.Resources.Networks = []
    return alloc, task


def _ctx(tmp_path, alloc, task):
    ad = AllocDir(str(tmp_path / "alloc" / alloc.ID))
    ad.build([task.Name])
    env = TaskEnv(node=mock.node(), task=task, alloc=alloc,
                  alloc_dir=ad.shared_dir,
                  task_dir=ad.task_dirs[task.Name])
    return ExecContext(alloc_dir=ad, alloc_id=alloc.ID, task_env=env)


class _Options:
    def __init__(self, opts=None):
        self.opts = opts or {}

    def read_option(self, key, default=""):
        return self.opts.get(key, default)


def _driver(opts=None):
    d = new_driver("docker", DriverContext())
    d.ctx.config = _Options(opts)
    return d


class TestDockerLifecycle:
    def test_fingerprint_reports_version(self, fake_docker):
        node = mock.node()
        d = _driver()
        assert d.fingerprint(_Options(), node) is True
        assert node.Attributes["driver.docker"] == "1"
        assert node.Attributes["driver.docker.version"] == "1.11.fake"

    def test_start_logs_wait_cleanup(self, fake_docker, tmp_path):
        """The full happy path: run -> log pump into FileRotator files ->
        wait -> exit 0 -> container removed (cleanup.container default)."""
        alloc, task = _task("fake/short", command="echo",
                            args=["${NOMAD_ALLOC_ID}"])
        ctx = _ctx(tmp_path, alloc, task)
        d = _driver()
        handle = d.start(ctx, task)
        res = handle.wait(timeout=10)
        assert res is not None and res.exit_code == 0
        # Log pump: container stdout/stderr landed in the alloc log dir,
        # with env interpolation applied to args.
        log_dir = ctx.alloc_dir.log_dir()

        def _read(kind):
            return b"".join(
                (p := os.path.join(log_dir, f)) and open(p, "rb").read()
                for f in sorted(os.listdir(log_dir))
                if f.startswith(f"{task.Name}.{kind}"))
        assert wait_for(lambda: b"out:fake/short:echo " + alloc.ID.encode()
                        in _read("stdout"), timeout=10)
        assert wait_for(lambda: b"err:fake/short" in _read("stderr"),
                        timeout=10)
        # Cleanup ran after self-exit (the _watch path, not kill).
        state = json.loads(
            (fake_docker / f"{handle.container_id}.json").read_text())
        assert wait_for(lambda: json.loads(
            (fake_docker / f"{handle.container_id}.json").read_text()
        )["removed"], timeout=10)
        assert state["flags"]["memory"] == f"{task.Resources.MemoryMB}m"
        assert state["flags"]["cpu_shares"] == str(task.Resources.CPU)
        assert any(v.endswith(":/alloc") for v in state["flags"]["volumes"])

    def test_kill_stops_container(self, fake_docker, tmp_path):
        alloc, task = _task("fake/long")
        ctx = _ctx(tmp_path, alloc, task)
        d = _driver()
        handle = d.start(ctx, task)
        # Event checks, not wall-clock margins: a long container has no
        # exit to wait out (poll the done event instantaneously), and the
        # stats sample is one subprocess round that can stall under suite
        # load — poll until a sample lands instead of asserting the first.
        assert handle.wait(timeout=0) is None  # still running
        assert wait_for(lambda: handle.stats() is not None, timeout=20,
                        msg="live stats sample")
        handle.kill(kill_timeout=1.0)
        res = handle.wait(timeout=10)
        assert res is not None and res.exit_code == 137
        argvs = [i["argv"] for i in _invocations(fake_docker)]
        assert any(a[0] == "stop" for a in argvs)

    def test_failing_container_reports_exit_code(self, fake_docker,
                                                 tmp_path):
        alloc, task = _task("fake/fail")
        ctx = _ctx(tmp_path, alloc, task)
        handle = _driver().start(ctx, task)
        res = handle.wait(timeout=10)
        assert res is not None and res.exit_code == 7

    def test_run_flags_network_labels_ports(self, fake_docker, tmp_path):
        from nomad_tpu.structs import NetworkResource, Port

        alloc, task = _task("fake/short", config={
            "network_mode": "host",
            "labels": {"team": "infra"},
            "port_map": {"db": 6379},
        })
        task.Resources.Networks = [NetworkResource(
            IP="10.0.0.1", ReservedPorts=[Port(Label="db", Value=21000)])]
        ctx = _ctx(tmp_path, alloc, task)
        handle = _driver().start(ctx, task)
        handle.wait(timeout=10)
        state = json.loads(
            (fake_docker / f"{handle.container_id}.json").read_text())
        assert state["flags"]["network"] == "host"
        assert "team=infra" in state["flags"]["labels"]
        assert "21000:6379" in state["flags"]["ports"]

    def test_endpoint_and_tls_options_reach_cli(self, fake_docker,
                                                tmp_path):
        """client options docker.endpoint / docker.cert.path /
        docker.tls.verify become DOCKER_* env on every CLI call
        (reference: docker.go:258-289 client init)."""
        alloc, task = _task("fake/short")
        ctx = _ctx(tmp_path, alloc, task)
        d = _driver({"docker.endpoint": "tcp://10.0.0.9:2376",
                     "docker.cert.path": "/etc/docker-certs",
                     "docker.tls.verify": "true"})
        handle = d.start(ctx, task)
        handle.wait(timeout=10)
        envs = [i["env"] for i in _invocations(fake_docker)
                if i["argv"][0] == "run"]
        assert envs and envs[0]["DOCKER_HOST"] == "tcp://10.0.0.9:2376"
        assert envs[0]["DOCKER_CERT_PATH"] == "/etc/docker-certs"
        assert envs[0]["DOCKER_TLS_VERIFY"] == "1"

    def test_registry_auth_passed_and_scrubbed(self, fake_docker,
                                               tmp_path):
        """Private-registry auth reaches `docker --config` as a
        credentials file that is deleted right after the run."""
        alloc, task = _task("fake/short", config={
            "auth": {"username": "u", "password": "p",
                     "server_address": "reg.example.com"}})
        ctx = _ctx(tmp_path, alloc, task)
        handle = _driver().start(ctx, task)
        handle.wait(timeout=10)
        auth = json.loads((fake_docker / "last_auth.json").read_text())
        assert "reg.example.com" in auth["auths"]
        # Scrubbed: no credentials at rest in the task dir.
        task_dir = ctx.alloc_dir.task_dirs[task.Name]
        assert not os.path.exists(os.path.join(task_dir, "docker-auth"))

    def test_exec_in_task(self, fake_docker, tmp_path):
        alloc, task = _task("fake/long")
        ctx = _ctx(tmp_path, alloc, task)
        handle = _driver().start(ctx, task)
        code, out = handle.exec_in_task("/bin/check", ["-v"], timeout=5)
        assert code == 0
        assert "exec:/bin/check -v" in out
        handle.kill(1.0)

    def test_reattach_by_handle_id(self, fake_docker, tmp_path):
        """Agent restart: a new handle opened from the persisted id keeps
        watching the same container."""
        alloc, task = _task("fake/long")
        ctx = _ctx(tmp_path, alloc, task)
        d = _driver()
        handle = d.start(ctx, task)
        hid = handle.id()
        re = d.open(ctx, hid)
        assert re.container_id == handle.container_id
        handle.kill(1.0)
        res = re.wait(timeout=10)
        assert res is not None and res.exit_code == 137

    def test_batched_stats_many(self, fake_docker, tmp_path):
        from nomad_tpu.client.driver.docker import DockerHandle

        handles = []
        for _ in range(3):
            alloc, task = _task("fake/long")
            ctx = _ctx(tmp_path, alloc, task)
            handles.append(_driver().start(ctx, task))
        stats = DockerHandle.stats_many(handles)
        assert len(stats) == 3
        for h in handles:
            assert stats[h.container_id]["cpu_percent"] == 5.0
            assert stats[h.container_id]["rss_bytes"] == 10 * 2**20
            h.kill(1.0)
