"""State store tests (shaped after reference nomad/state/state_store_test.go:
every mutation asserts results AND watch firing)."""

import threading

import pytest

from nomad_tpu import mock
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.state.watch import Item
from nomad_tpu.structs.structs import (
    AllocClientStatusComplete,
    AllocClientStatusFailed,
    AllocClientStatusRunning,
    AllocDesiredStatusStop,
    EvalStatusComplete,
    JobStatusDead,
    JobStatusPending,
    JobStatusRunning,
    NodeStatusDown,
    NodeStatusReady,
)
from nomad_tpu.structs import PeriodicLaunch, TaskState


class WatchAsserter:
    """Registers on items and asserts which fired (reference: notifyTest)."""

    def __init__(self, store, *items):
        self.store = store
        self.events = {item: threading.Event() for item in items}
        for item, ev in self.events.items():
            store.watch([item], ev)

    def assert_fired(self, *items):
        for item in items:
            assert self.events[item].is_set(), f"watch did not fire: {item}"

    def assert_not_fired(self, *items):
        for item in items:
            assert not self.events[item].is_set(), f"watch fired: {item}"


class TestNodes:
    def test_upsert_get_delete(self):
        s = StateStore()
        n = mock.node()
        w = WatchAsserter(s, Item(table="nodes"), Item(node=n.ID))
        s.upsert_node(1000, n)
        w.assert_fired(Item(table="nodes"), Item(node=n.ID))
        out = s.node_by_id(n.ID)
        assert out.CreateIndex == 1000 and out.ModifyIndex == 1000
        assert s.get_index("nodes") == 1000
        s.delete_node(1001, n.ID)
        assert s.node_by_id(n.ID) is None
        assert s.get_index("nodes") == 1001

    def test_update_status_and_drain(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(1, n)
        s.update_node_status(2, n.ID, NodeStatusDown)
        assert s.node_by_id(n.ID).Status == NodeStatusDown
        s.update_node_drain(3, n.ID, True)
        out = s.node_by_id(n.ID)
        assert out.Drain is True and out.ModifyIndex == 3

    def test_missing_node_raises(self):
        s = StateStore()
        with pytest.raises(KeyError):
            s.update_node_status(2, "nope", NodeStatusReady)

    def test_upsert_preserves_create_index(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(5, n)
        n2 = n.copy()
        s.upsert_node(9, n2)
        assert s.node_by_id(n.ID).CreateIndex == 5
        assert s.node_by_id(n.ID).ModifyIndex == 9


class TestJobs:
    def test_upsert_job_status_derivation(self):
        s = StateStore()
        j = mock.job()
        w = WatchAsserter(s, Item(table="jobs"), Item(job=j.ID))
        s.upsert_job(1, j)
        w.assert_fired(Item(table="jobs"), Item(job=j.ID))
        assert s.job_by_id(j.ID).Status == JobStatusPending

    def test_periodic_job_running(self):
        s = StateStore()
        j = mock.periodic_job()
        s.upsert_job(1, j)
        assert s.job_by_id(j.ID).Status == JobStatusRunning

    def test_job_running_with_alloc(self):
        s = StateStore()
        j = mock.job()
        s.upsert_job(1, j)
        a = mock.alloc()
        a.JobID = j.ID
        a.Job = j
        s.upsert_allocs(2, [a])
        assert s.job_by_id(j.ID).Status == JobStatusRunning

    def test_job_dead_when_all_terminal(self):
        s = StateStore()
        j = mock.job()
        s.upsert_job(1, j)
        a = mock.alloc()
        a.JobID = j.ID
        s.upsert_allocs(2, [a])
        done = s.alloc_by_id(a.ID).copy()
        done.ClientStatus = AllocClientStatusComplete
        s.update_alloc_from_client(3, done)
        assert s.job_by_id(j.ID).Status == JobStatusDead

    def test_delete_job(self):
        s = StateStore()
        j = mock.job()
        s.upsert_job(1, j)
        s.delete_job(2, j.ID)
        assert s.job_by_id(j.ID) is None
        with pytest.raises(KeyError):
            s.delete_job(3, j.ID)

    def test_jobs_by_scheduler_and_periodic(self):
        s = StateStore()
        j1, j2 = mock.job(), mock.system_job()
        j3 = mock.periodic_job()
        for i, j in enumerate([j1, j2, j3]):
            s.upsert_job(i + 1, j)
        assert {j.ID for j in s.jobs_by_scheduler("service")} == {j1.ID}
        assert {j.ID for j in s.jobs_by_scheduler("system")} == {j2.ID}
        assert {j.ID for j in s.jobs_by_periodic(True)} == {j3.ID}


class TestEvals:
    def test_upsert_and_by_job(self):
        s = StateStore()
        e = mock.eval()
        w = WatchAsserter(s, Item(table="evals"), Item(eval=e.ID))
        s.upsert_evals(100, [e])
        w.assert_fired(Item(table="evals"), Item(eval=e.ID))
        assert s.eval_by_id(e.ID).CreateIndex == 100
        assert [x.ID for x in s.evals_by_job(e.JobID)] == [e.ID]

    def test_eval_makes_job_pending(self):
        s = StateStore()
        j = mock.job()
        s.upsert_job(1, j)
        a = mock.alloc()
        a.JobID = j.ID
        s.upsert_allocs(2, [a])
        assert s.job_by_id(j.ID).Status == JobStatusRunning
        # Terminal alloc + fresh pending eval -> pending again
        done = s.alloc_by_id(a.ID).copy()
        done.ClientStatus = AllocClientStatusFailed
        s.update_alloc_from_client(3, done)
        e = mock.eval()
        e.JobID = j.ID
        s.upsert_evals(4, [e])
        assert s.job_by_id(j.ID).Status == JobStatusPending

    def test_delete_eval_with_allocs(self):
        s = StateStore()
        e = mock.eval()
        a = mock.alloc()
        a.EvalID = e.ID
        s.upsert_evals(1, [e])
        s.upsert_allocs(2, [a])
        s.delete_eval(3, [e.ID], [a.ID])
        assert s.eval_by_id(e.ID) is None
        assert s.alloc_by_id(a.ID) is None
        assert s.allocs_by_eval(e.ID) == []


class TestAllocs:
    def test_upsert_and_indexes(self):
        s = StateStore()
        a = mock.alloc()
        w = WatchAsserter(s, Item(table="allocs"), Item(alloc=a.ID),
                          Item(alloc_node=a.NodeID), Item(alloc_job=a.JobID),
                          Item(alloc_eval=a.EvalID))
        s.upsert_allocs(50, [a])
        w.assert_fired(Item(table="allocs"), Item(alloc=a.ID),
                       Item(alloc_node=a.NodeID), Item(alloc_job=a.JobID),
                       Item(alloc_eval=a.EvalID))
        assert [x.ID for x in s.allocs_by_node(a.NodeID)] == [a.ID]
        assert [x.ID for x in s.allocs_by_job(a.JobID)] == [a.ID]
        assert [x.ID for x in s.allocs_by_eval(a.EvalID)] == [a.ID]

    def test_terminal_filter(self):
        s = StateStore()
        a1, a2 = mock.alloc(), mock.alloc()
        a2.NodeID = a1.NodeID
        a2.DesiredStatus = AllocDesiredStatusStop
        s.upsert_allocs(1, [a1, a2])
        assert {x.ID for x in s.allocs_by_node_terminal(a1.NodeID, False)} == {a1.ID}
        assert {x.ID for x in s.allocs_by_node_terminal(a1.NodeID, True)} == {a2.ID}

    def test_server_upsert_keeps_client_state(self):
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1, [a])
        client_view = s.alloc_by_id(a.ID).copy()
        client_view.ClientStatus = AllocClientStatusRunning
        client_view.TaskStates = {"web": TaskState(State="running")}
        s.update_alloc_from_client(2, client_view)
        # Server-side re-upsert (plan applier) must not clobber client status.
        server_view = a.copy()
        s.upsert_allocs(3, [server_view])
        out = s.alloc_by_id(a.ID)
        assert out.ClientStatus == AllocClientStatusRunning
        assert out.TaskStates["web"].State == "running"
        assert out.ModifyIndex == 3

    def test_update_from_client_missing(self):
        s = StateStore()
        with pytest.raises(KeyError):
            s.update_alloc_from_client(1, mock.alloc())


class TestSnapshots:
    def test_snapshot_isolation(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(1, n)
        snap = s.snapshot()
        s.update_node_status(2, n.ID, NodeStatusDown)
        assert s.node_by_id(n.ID).Status == NodeStatusDown
        assert snap.node_by_id(n.ID).Status == NodeStatusReady
        assert snap.latest_index() == 1

    def test_snapshot_sees_deletes_correctly(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(1, n)
        snap = s.snapshot()
        s.delete_node(2, n.ID)
        assert s.node_by_id(n.ID) is None
        assert snap.node_by_id(n.ID) is not None
        assert len(snap.nodes()) == 1
        assert len(s.nodes()) == 0

    def test_snapshot_members(self):
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1, [a])
        snap = s.snapshot()
        a2 = mock.alloc()
        a2.NodeID = a.NodeID
        s.upsert_allocs(2, [a2])
        assert len(s.allocs_by_node(a.NodeID)) == 2
        assert len(snap.allocs_by_node(a.NodeID)) == 1

    def test_compact_preserves_live_snapshots(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(1, n)
        snap = s.snapshot()
        s.update_node_status(2, n.ID, NodeStatusDown)
        s.compact()
        assert snap.node_by_id(n.ID).Status == NodeStatusReady
        del snap
        s.compact()
        # After the snapshot is gone, history may be dropped; live view intact.
        assert s.node_by_id(n.ID).Status == NodeStatusDown

    def test_compact_removes_deleted(self):
        s = StateStore()
        e = mock.eval()
        a = mock.alloc()
        a.EvalID = e.ID
        s.upsert_evals(1, [e])
        s.upsert_allocs(2, [a])
        s.delete_eval(3, [e.ID], [a.ID])
        s.compact()
        assert s._tables["allocs"].chains == {}
        assert s._member_sets["alloc_eval"] == {}


class TestRestore:
    def test_roundtrip(self):
        s = StateStore()
        n, j, e, a = mock.node(), mock.job(), mock.eval(), mock.alloc()
        s.upsert_node(1, n)
        s.upsert_job(2, j)
        s.upsert_evals(3, [e])
        s.upsert_allocs(4, [a])
        s.upsert_periodic_launch(5, PeriodicLaunch(ID=j.ID, Launch=123.0))

        s2 = StateStore()
        r = s2.restore()
        snap = s.snapshot()
        for node in snap.nodes():
            r.node_restore(node)
        for job in snap.jobs():
            r.job_restore(job)
        for ev in snap.evals():
            r.eval_restore(ev)
        for alloc in snap.allocs():
            r.alloc_restore(alloc)
        for pl in snap.periodic_launches():
            r.periodic_launch_restore(pl)
        for t in ("nodes", "jobs", "evals", "allocs", "periodic_launch"):
            r.index_restore(t, s.get_index(t))
        r.commit()

        assert s2.node_by_id(n.ID) is not None
        assert s2.job_by_id(j.ID) is not None
        assert [x.ID for x in s2.evals_by_job(e.JobID)] == [e.ID]
        assert [x.ID for x in s2.allocs_by_node(a.NodeID)] == [a.ID]
        assert s2.periodic_launch_by_id(j.ID).Launch == 123.0
        assert s2.latest_index() == s.latest_index()


class TestBlockingQueryPattern:
    def test_watch_wakes_blocked_reader(self):
        s = StateStore()
        n = mock.node()
        s.upsert_node(1, n)
        ev = threading.Event()
        s.watch([Item(node=n.ID)], ev)
        result = {}

        def writer():
            s.update_node_status(2, n.ID, NodeStatusDown)

        t = threading.Timer(0.05, writer)
        t.start()
        assert ev.wait(2.0), "blocking query never woke"
        result["status"] = s.node_by_id(n.ID).Status
        assert result["status"] == NodeStatusDown
        s.stop_watch([Item(node=n.ID)], ev)
