"""Computed-class memoization: correctness parity + work bound
(reference: scheduler/stack_test.go:13-53's paired with/without-computed-
class benchmark — here asserted as invariants instead of timings)."""

import numpy as np

from nomad_tpu import mock
from nomad_tpu.structs import Constraint, compute_node_class
from nomad_tpu.tensor import TensorIndex
from nomad_tpu.tensor import constraints as cons_mod
from nomad_tpu.tensor.constraints import (
    ClassEligibility,
    node_meets_constraints,
)


def _mixed_nodes(n=120, n_classes=4):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.Meta["rack"] = f"r{i % n_classes}"
        compute_node_class(node)
        nodes.append(node)
    return nodes


class TestComputedClassParity:
    def test_masks_match_per_node_evaluation(self):
        """Class-memoized eligibility must equal brute-force per-node
        constraint evaluation for memoizable constraints."""
        nodes = _mixed_nodes()
        tindex = TensorIndex()
        for node in nodes:
            tindex.nt.upsert_node(node)
        elig = ClassEligibility(tindex.nt, nodes)
        constraints = [
            Constraint(LTarget="${meta.rack}", RTarget="r1", Operand="="),
            Constraint(LTarget="${attr.arch}", RTarget="x86",
                       Operand="="),
        ]
        mask, _, _ = elig.job_mask("job-x", constraints)
        for node in nodes:
            row = tindex.nt.row_of[node.ID]
            assert mask[row] == node_meets_constraints(node, constraints), \
                node.Meta
        # Exactly the r1 class is eligible.
        eligible = {nodes[i].Meta["rack"]
                    for i, node in enumerate(nodes)
                    if mask[tindex.nt.row_of[node.ID]]}
        assert eligible == {"r1"}

    def test_constraint_evaluations_scale_with_classes_not_nodes(self):
        """The with-computed-class path evaluates constraints once per
        CLASS; without memoization it would be once per NODE (the 10-100x
        the reference's paired benchmark demonstrates)."""
        nodes = _mixed_nodes(n=200, n_classes=5)
        tindex = TensorIndex()
        for node in nodes:
            tindex.nt.upsert_node(node)
        elig = ClassEligibility(tindex.nt, nodes)

        calls = {"n": 0}
        orig = cons_mod.node_meets_constraints

        def counting(node, constraints):
            calls["n"] += 1
            return orig(node, constraints)

        cons_mod.node_meets_constraints = counting
        try:
            constraints = [Constraint(LTarget="${meta.rack}", RTarget="r2",
                                      Operand="=")]
            elig.job_mask("job-y", constraints)
        finally:
            cons_mod.node_meets_constraints = orig
        assert 0 < calls["n"] <= 5, calls  # one per class, never per node

    def test_escaped_constraints_fall_back_per_node(self):
        """unique.* targets can't memoize by class: each node is evaluated
        individually and the mask stays exact."""
        nodes = _mixed_nodes(n=20, n_classes=2)
        tindex = TensorIndex()
        for node in nodes:
            tindex.nt.upsert_node(node)
        elig = ClassEligibility(tindex.nt, nodes)
        target = nodes[7]
        constraints = [Constraint(
            LTarget="${attr.unique.hostname}",
            RTarget=target.Attributes.get("unique.hostname", ""),
            Operand="=")]
        mask, _, escaped = elig.job_mask("job-z", constraints)
        expected = np.zeros_like(mask)
        for node in nodes:
            if node_meets_constraints(node, constraints):
                expected[tindex.nt.row_of[node.ID]] = True
        assert (mask == expected).all()
