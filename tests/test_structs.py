"""Data model tests (shaped after reference nomad/structs/*_test.go)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import (
    Allocation,
    Bitmap,
    Constraint,
    Job,
    NetworkIndex,
    NetworkResource,
    Node,
    PeriodicConfig,
    Port,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    allocs_fit,
    compute_class,
    decode,
    encode,
    escaped_constraints,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from nomad_tpu.structs.structs import (
    MINUTE,
    SECOND,
    AllocClientStatusComplete,
    AllocDesiredStatusEvict,
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    JobTypeBatch,
    JobTypeService,
    PeriodicSpecTest,
    RestartPolicyModeDelay,
    RestartPolicyModeFail,
)
from nomad_tpu.structs.version import check_version_constraint


class TestJobValidate:
    def test_empty_job_has_errors(self):
        errs = Job().validate()
        text = "\n".join(errs)
        assert "job region" in text
        assert "job ID" in text
        assert "job name" in text
        assert "job type" in text
        assert "priority" in text
        assert "datacenters" in text
        assert "task groups" in text

    def test_mock_job_valid(self):
        assert mock.job().validate() == []

    def test_duplicate_task_group(self):
        j = mock.job()
        j.TaskGroups.append(j.TaskGroups[0])
        assert any("defined multiple times" in e for e in j.validate())

    def test_system_job_count(self):
        j = mock.system_job()
        j.TaskGroups[0].Count = 5
        j.init_fields()
        assert any("should have a count of 1" in e for e in j.validate())

    def test_periodic_only_batch(self):
        j = mock.job()
        j.Periodic = PeriodicConfig(Enabled=True, Spec="* * * * *")
        assert any("batch" in e for e in j.validate())
        j.Type = JobTypeBatch
        assert j.validate() == []


class TestTaskGroupValidate:
    def test_empty(self):
        errs = TaskGroup(Count=0).validate()
        text = "\n".join(errs)
        assert "task group name" in text
        assert "count must be positive" in text
        assert "Missing tasks" in text

    def test_duplicate_tasks(self):
        tg = mock.job().TaskGroups[0]
        tg.Tasks.append(tg.Tasks[0])
        assert any("defined multiple times" in e for e in tg.validate())


class TestTaskValidate:
    def test_empty(self):
        errs = Task().validate()
        text = "\n".join(errs)
        assert "task name" in text
        assert "task driver" in text
        assert "task resources" in text

    def test_log_storage_vs_disk(self):
        t = mock.job().TaskGroups[0].Tasks[0]
        t.Resources.DiskMB = 10  # below 10 files x 10MB log budget
        assert any("log storage" in e for e in t.validate())


class TestRestartPolicy:
    def test_modes(self):
        ok = RestartPolicy(Attempts=3, Interval=10 * MINUTE, Delay=1 * MINUTE,
                           Mode=RestartPolicyModeDelay)
        assert ok.validate() == []
        bad = RestartPolicy(Mode="bogus")
        assert any("Unsupported restart mode" in e for e in bad.validate())

    def test_ambiguous(self):
        p = RestartPolicy(Attempts=0, Mode=RestartPolicyModeDelay)
        assert any("ambiguous" in e for e in p.validate())
        p2 = RestartPolicy(Attempts=0, Mode=RestartPolicyModeFail)
        assert p2.validate() == []

    def test_too_many_restarts_in_interval(self):
        p = RestartPolicy(Attempts=10, Interval=5 * SECOND, Delay=1 * SECOND,
                          Mode=RestartPolicyModeDelay)
        assert any("can't restart" in e for e in p.validate())


class TestResources:
    def test_superset(self):
        big = Resources(CPU=2000, MemoryMB=2048, DiskMB=10000, IOPS=100)
        small = Resources(CPU=2000, MemoryMB=2048, DiskMB=10000, IOPS=100)
        assert big.superset(small) == (True, "")
        small.CPU = 2001
        assert big.superset(small) == (False, "cpu exhausted")
        small.CPU = 100
        small.MemoryMB = 4096
        assert big.superset(small) == (False, "memory exhausted")

    def test_add(self):
        r = Resources(CPU=100, MemoryMB=100)
        r.add(Resources(CPU=50, MemoryMB=25, DiskMB=100, IOPS=5))
        assert (r.CPU, r.MemoryMB, r.DiskMB, r.IOPS) == (150, 125, 100, 5)

    def test_min_resources(self):
        assert Resources(CPU=10, MemoryMB=5, DiskMB=5, IOPS=-1).meets_min_resources()
        assert Resources.default().meets_min_resources() == []


class TestScoreFit:
    def _node(self):
        return Node(Resources=Resources(CPU=4096, MemoryMB=8192),
                    Reserved=Resources(CPU=2048, MemoryMB=4096))

    def test_perfect_fit_scores_18(self):
        # Node has 2048 CPU / 4096 MB free after reservation; full usage => 18.
        util = Resources(CPU=2048, MemoryMB=4096)
        assert score_fit(self._node(), util) == pytest.approx(18.0)

    def test_empty_util_scores_0(self):
        assert score_fit(self._node(), Resources()) == pytest.approx(0.0)

    def test_half_util_middling(self):
        s = score_fit(self._node(), Resources(CPU=1024, MemoryMB=2048))
        assert 0 < s < 18
        # 20 - 2*10^0.5
        assert s == pytest.approx(20.0 - 2 * 10 ** 0.5)

    def test_fully_reserved_node_no_crash(self):
        n = Node(Resources=Resources(CPU=4096, MemoryMB=8192),
                 Reserved=Resources(CPU=4096, MemoryMB=8192))
        # Overfit (util on a zero-headroom node) clamps to the 18.0 overfit
        # ceiling, mirroring Go's Inf arithmetic; 0/0 (NaN) sanitizes to 0.
        assert score_fit(n, Resources(CPU=100, MemoryMB=100)) == 18.0
        assert score_fit(n, Resources()) == 0.0


class TestAllocsFit:
    def test_fit_and_overcommit(self):
        n = mock.node()
        a = mock.alloc()
        a.Resources = Resources(
            CPU=2000, MemoryMB=2048, DiskMB=5000,
            Networks=[NetworkResource(Device="eth0", IP="192.168.0.100",
                                      MBits=50, ReservedPorts=[Port("main", 8000)])],
        )
        a.TaskResources = {}
        fit, dim, used = allocs_fit(n, [a])
        assert fit, dim
        assert used.CPU == 2000 + 100  # alloc + reserved
        fit, dim, _ = allocs_fit(n, [a, a])
        assert not fit
        assert dim  # some dimension exhausted

    def test_filter_terminal(self):
        run = mock.alloc()
        stopped = mock.alloc()
        stopped.DesiredStatus = AllocDesiredStatusStop
        complete = mock.alloc()
        complete.ClientStatus = AllocClientStatusComplete
        evicted = mock.alloc()
        evicted.DesiredStatus = AllocDesiredStatusEvict
        out = filter_terminal_allocs([run, stopped, complete, evicted])
        assert out == [run]

    def test_remove_allocs(self):
        a, b, c = mock.alloc(), mock.alloc(), mock.alloc()
        assert remove_allocs([a, b, c], [b]) == [a, c]


class TestNetworkIndex:
    def test_set_node_and_collision(self):
        idx = NetworkIndex()
        n = mock.node()
        assert idx.set_node(n) is False
        assert idx.avail_bandwidth["eth0"] == 1000
        assert idx.used_ports["192.168.0.100"].check(22)

    def test_assign_network_static_and_dynamic(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = NetworkResource(MBits=50, ReservedPorts=[Port("main", 8000)],
                              DynamicPorts=[Port("http", 0)])
        offer = idx.assign_network(ask)
        assert offer.IP == "192.168.0.100"
        assert offer.ReservedPorts[0].Value == 8000
        assert 20000 <= offer.DynamicPorts[0].Value < 60000

    def test_assign_network_reserved_collision(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        ask = NetworkResource(MBits=10, ReservedPorts=[Port("ssh", 22)])
        with pytest.raises(ValueError, match="reserved port collision"):
            idx.assign_network(ask)

    def test_assign_network_bandwidth_exceeded(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        with pytest.raises(ValueError, match="bandwidth exceeded"):
            idx.assign_network(NetworkResource(MBits=2000))

    def test_overcommitted(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        idx.add_reserved(NetworkResource(Device="eth0", IP="192.168.0.100", MBits=2000))
        assert idx.overcommitted()


class TestBitmap:
    def test_basics(self):
        b = Bitmap(65536)
        assert not b.check(42)
        b.set(42)
        b.set(65535)
        assert b.check(42) and b.check(65535)
        b.clear()
        assert not b.check(42)


class TestComputedClass:
    def test_same_attrs_same_class(self):
        n1, n2 = mock.node(), mock.node()
        assert compute_class(n1) == compute_class(n2)

    def test_unique_keys_excluded(self):
        n1, n2 = mock.node(), mock.node()
        n2.Attributes["unique.hostname"] = "xyz"
        assert compute_class(n1) == compute_class(n2)

    def test_differs_on_meta(self):
        n1, n2 = mock.node(), mock.node()
        n2.Meta["database"] = "postgres"
        assert compute_class(n1) != compute_class(n2)

    def test_escaped_constraints(self):
        cs = [
            Constraint(LTarget="${attr.kernel.name}", RTarget="linux", Operand="="),
            Constraint(LTarget="${attr.unique.network.ip-address}", RTarget="x", Operand="="),
            Constraint(LTarget="${node.unique.id}", RTarget="y", Operand="="),
        ]
        esc = escaped_constraints(cs)
        assert len(esc) == 2


class TestVersionConstraint:
    def test_basic(self):
        assert check_version_constraint("1.2.3", ">= 1.0, < 2.0")
        assert not check_version_constraint("2.1.0", ">= 1.0, < 2.0")
        assert check_version_constraint("0.4.0", "~> 0.4")
        assert check_version_constraint("1.2.4", "> 1.2.3")
        assert not check_version_constraint("banana", "> 1.0")

    def test_pessimistic_single_segment(self):
        # "~> 1" means >=1, <2 (go-version semantics).
        assert check_version_constraint("1.9.9", "~> 1")
        assert not check_version_constraint("2.0.0", "~> 1")
        assert check_version_constraint("1.2.9", "~> 1.2.3")
        assert not check_version_constraint("1.3.0", "~> 1.2.3")

    def test_prerelease_ordering_semver(self):
        # Dotted numeric identifiers compare numerically...
        assert check_version_constraint("1.0.0-rc.10", "> 1.0.0-rc.9")
        # ...but alphanumeric identifiers compare ASCII-lexically (semver):
        # "rc10" < "rc9".
        assert not check_version_constraint("1.0.0-rc10", "> 1.0.0-rc9")
        assert check_version_constraint("1.0.0", "> 1.0.0-rc.10")


class TestPeriodic:
    def test_cron_next(self):
        p = PeriodicConfig(Enabled=True, Spec="*/30 * * * *")
        assert p.validate() == []
        import time
        nxt = p.next(time.time())
        assert nxt > time.time()
        lt = time.localtime(nxt)
        assert lt.tm_min in (0, 30) and lt.tm_sec == 0

    def test_test_spec(self):
        p = PeriodicConfig(Enabled=True, SpecType=PeriodicSpecTest, Spec="100,200,300")
        assert p.next(150) == 200.0
        assert p.next(500) == 0.0

    def test_invalid_cron(self):
        p = PeriodicConfig(Enabled=True, Spec="this is not cron")
        assert p.validate()

    def test_cron_dow_seven_is_sunday(self):
        # 5-7 (Fri-Sun) must parse; 7 is an alias for Sunday.
        assert PeriodicConfig(Enabled=True, Spec="0 0 * * 5-7").validate() == []
        from nomad_tpu.structs.cron import CronExpr
        e = CronExpr.parse("0 0 * * 7")
        assert 0 in e.dow and 7 not in e.dow


class TestEvalAndPlan:
    def test_should_enqueue_and_block(self):
        e = mock.eval()
        assert e.should_enqueue()
        assert not e.should_block()
        e.Status = "blocked"
        assert e.should_block()
        assert not e.should_enqueue()

    def test_make_plan(self):
        e = mock.eval()
        j = mock.job()
        p = e.make_plan(j)
        assert p.EvalID == e.ID
        assert p.Job.ID == j.ID

    def test_plan_append_pop(self):
        p = mock.plan()
        a = mock.alloc()
        assert p.is_no_op()
        p.append_update(a, AllocDesiredStatusStop, "test")
        assert not p.is_no_op()
        assert p.NodeUpdate[a.NodeID][0].Job is None
        assert p.NodeUpdate[a.NodeID][0].DesiredStatus == AllocDesiredStatusStop
        p.pop_update(a)
        assert p.is_no_op()

    def test_create_blocked_eval(self):
        e = mock.eval()
        b = e.create_blocked_eval({"v1:123": True}, False)
        assert b.Status == "blocked"
        assert b.PreviousEval == e.ID
        assert b.ClassEligibility == {"v1:123": True}


class TestCodec:
    def test_roundtrip_job(self):
        j = mock.job()
        buf = encode(j)
        j2 = decode(Job, buf)
        assert j2.ID == j.ID
        assert j2.TaskGroups[0].Tasks[0].Resources.CPU == 500
        assert j2.TaskGroups[0].Tasks[0].Services[0].Checks[0].Interval == 30 * SECOND
        assert encode(j2) == buf

    def test_roundtrip_alloc(self):
        a = mock.alloc()
        a2 = decode(Allocation, encode(a))
        assert a2.TaskResources["web"].Networks[0].ReservedPorts[0].Value == 5000
        assert a2.Job.Type == JobTypeService


class TestNetworkIndexReferenceGrid:
    """The reference's full network_test.go grid (AddAllocs accumulation,
    AddReserved repeat-collision, yieldIP CIDR walk, and the multi-IP
    AssignNetwork scenarios), ported case for case."""

    def _node30(self):
        from nomad_tpu.structs import Node, Resources

        return Node(
            Resources=Resources(Networks=[NetworkResource(
                Device="eth0", CIDR="192.168.0.100/30", MBits=1000)]),
            Reserved=Resources(Networks=[NetworkResource(
                Device="eth0", IP="192.168.0.100", MBits=1,
                ReservedPorts=[Port("ssh", 22)])]),
        )

    def _allocs(self):
        from nomad_tpu.structs import Allocation, Resources

        return [
            Allocation(TaskResources={"web": Resources(Networks=[
                NetworkResource(Device="eth0", IP="192.168.0.100",
                                MBits=20,
                                ReservedPorts=[Port("one", 8000),
                                               Port("two", 9000)])])}),
            Allocation(TaskResources={"api": Resources(Networks=[
                NetworkResource(Device="eth0", IP="192.168.0.100",
                                MBits=50,
                                ReservedPorts=[Port("main", 10000)])])}),
        ]

    def test_add_allocs_accumulates(self):
        """(reference: TestNetworkIndex_AddAllocs)"""
        idx = NetworkIndex()
        assert idx.add_allocs(self._allocs()) is False
        assert idx.used_bandwidth["eth0"] == 70
        for port in (8000, 9000, 10000):
            assert idx.used_ports["192.168.0.100"].check(port)

    def test_add_reserved_collides_on_repeat(self):
        """(reference: TestNetworkIndex_AddReserved)"""
        idx = NetworkIndex()
        reserved = NetworkResource(Device="eth0", IP="192.168.0.100",
                                   MBits=20,
                                   ReservedPorts=[Port("one", 8000),
                                                  Port("two", 9000)])
        assert idx.add_reserved(reserved) is False
        assert idx.used_bandwidth["eth0"] == 20
        assert idx.used_ports["192.168.0.100"].check(8000)
        assert idx.used_ports["192.168.0.100"].check(9000)
        # Same reservation again: collision reported.
        assert idx.add_reserved(reserved) is True

    def test_yield_ip_walks_cidr(self):
        """(reference: TestNetworkIndex_yieldIP)"""
        idx = NetworkIndex()
        idx.set_node(self._node30())
        ips = [ip for _, ip in idx._yield_ips()]
        assert ips == ["192.168.0.100", "192.168.0.101",
                       "192.168.0.102", "192.168.0.103"]

    def test_assign_network_grid(self):
        """(reference: TestNetworkIndex_AssignNetwork): a used reserved
        port pushes the offer to the NEXT IP of the CIDR; dynamic ports
        land on the first IP; bandwidth exhaustion reports exactly
        'bandwidth exceeded'."""
        import random as _random

        idx = NetworkIndex()
        idx.set_node(self._node30())
        idx.add_allocs(self._allocs())

        # Reserved port 8000 is used on .100 -> offer comes from .101.
        offer = idx.assign_network(
            NetworkResource(ReservedPorts=[Port("main", 8000)]),
            rng=_random.Random(1))
        assert offer.IP == "192.168.0.101"
        assert [(p.Label, p.Value) for p in offer.ReservedPorts] == \
            [("main", 8000)]

        # Dynamic ports fit on the first IP.
        offer = idx.assign_network(
            NetworkResource(DynamicPorts=[Port("http", 0),
                                          Port("https", 0),
                                          Port("admin", 0)]),
            rng=_random.Random(1))
        assert offer.IP == "192.168.0.100"
        assert len(offer.DynamicPorts) == 3
        values = [p.Value for p in offer.DynamicPorts]
        assert all(v > 0 for v in values)
        assert len(set(values)) == 3  # no duplicate host ports

        # Reserved + dynamic together, free reserved port -> first IP.
        offer = idx.assign_network(
            NetworkResource(ReservedPorts=[Port("main", 2345)],
                            DynamicPorts=[Port("http", 0),
                                          Port("https", 0),
                                          Port("admin", 0)]),
            rng=_random.Random(1))
        assert offer.IP == "192.168.0.100"
        assert [(p.Label, p.Value) for p in offer.ReservedPorts] == \
            [("main", 2345)]

        # Too much bandwidth: the exact reference error.
        with pytest.raises(ValueError, match="bandwidth exceeded"):
            idx.assign_network(NetworkResource(MBits=1000))
