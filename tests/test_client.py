"""Client agent tests (shaped after reference client/*_test.go)."""

import os
import tempfile

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig, InProcServerChannel
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.env import TaskEnv
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.client.logs import FileRotator
from nomad_tpu.client.restarts import NO_RESTART, RESTART_WAIT, RestartTracker
from nomad_tpu.jobspec import parse_job
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import Node, Resources, RestartPolicy
from nomad_tpu.structs.structs import (
    SECOND,
    JobTypeBatch,
    JobTypeService,
    NodeStatusReady,
    RestartPolicyModeFail,
)


from helpers import wait_for  # noqa: E402


class TestFingerprint:
    def test_basics(self):
        node = Node(Resources=Resources())
        results = fingerprint_node(node, None)
        assert results["arch"] and results["cpu"] and results["memory"]
        assert node.Attributes["kernel.name"]
        assert int(node.Attributes["cpu.numcores"]) >= 1
        assert node.Resources.CPU > 0
        assert node.Resources.MemoryMB > 0
        assert "unique.hostname" in node.Attributes


class TestAllocDir:
    def test_build_and_fs(self):
        with tempfile.TemporaryDirectory() as tmp:
            ad = AllocDir(os.path.join(tmp, "a1"))
            ad.build(["web", "db"])
            assert os.path.isdir(os.path.join(ad.shared_dir, "logs"))
            assert os.path.isdir(os.path.join(ad.task_dirs["web"], "local"))
            with open(os.path.join(ad.shared_dir, "data", "x.txt"), "w") as f:
                f.write("hello")
            infos = ad.list_dir("alloc/data")
            assert infos[0].Name == "x.txt" and infos[0].Size == 5
            assert ad.read_at("alloc/data/x.txt", 1, 3) == b"ell"
            with pytest.raises(PermissionError):
                ad.read_at("../../etc/passwd")
            ad.destroy()
            assert not os.path.exists(ad.alloc_dir)


class TestTaskEnv:
    def test_env_and_interpolation(self):
        node = mock.node()
        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        env = TaskEnv(node=node, task=task, alloc=alloc,
                      alloc_dir="/alloc", task_dir="/task")
        built = env.build_env()
        assert built["NOMAD_ALLOC_ID"] == alloc.ID
        assert built["NOMAD_TASK_DIR"] == "/task"
        assert built["NOMAD_MEMORY_LIMIT"] == "256"
        assert built["FOO"] == "bar"
        # Port env vars from assigned resources; the label's case is
        # preserved (reference: env.go:140 — jobs use ${NOMAD_PORT_http}).
        assert built["NOMAD_PORT_main"] == "5000"
        assert built["NOMAD_IP_main"] == "192.168.0.100"
        # Interpolation of node attrs/meta.
        assert env.replace("${attr.kernel.name}") == "linux"
        assert env.replace("${meta.pci-dss}") == "true"
        assert env.replace("${node.datacenter}") == "dc1"
        assert env.replace("no vars here") == "no vars here"


class TestFileRotator:
    def test_rotation(self):
        with tempfile.TemporaryDirectory() as tmp:
            r = FileRotator(tmp, "task.stdout", max_files=2, max_size_mb=1)
            chunk = b"x" * (512 * 1024)
            for _ in range(6):  # 3MB total -> rotates twice, keeps 2 files
                r.write(chunk)
            r.close()
            files = sorted(os.listdir(tmp))
            assert len(files) == 2
            assert files[-1].startswith("task.stdout.")


class TestRestartTracker:
    def test_batch_success_no_restart(self):
        rt = RestartTracker(RestartPolicy(Attempts=3, Interval=60 * SECOND,
                                          Delay=1 * SECOND, Mode="delay"),
                            JobTypeBatch)
        assert rt.next_restart(0)[0] == NO_RESTART

    def test_service_restarts_with_delay(self):
        rt = RestartTracker(RestartPolicy(Attempts=2, Interval=3600 * SECOND,
                                          Delay=1 * SECOND, Mode="delay"),
                            JobTypeService)
        decision, wait = rt.next_restart(1)
        assert decision == RESTART_WAIT
        assert 1.0 <= wait <= 1.3

    def test_fail_mode_stops(self):
        rt = RestartTracker(RestartPolicy(Attempts=1, Interval=3600 * SECOND,
                                          Delay=1 * SECOND,
                                          Mode=RestartPolicyModeFail),
                            JobTypeService)
        assert rt.next_restart(1)[0] == RESTART_WAIT
        assert rt.next_restart(1)[0] == NO_RESTART


@pytest.fixture
def dev_cluster(tmp_path):
    srv = Server(ServerConfig(num_schedulers=2))
    srv.establish_leadership()
    cfg = ClientConfig(state_dir=str(tmp_path / "state"),
                       alloc_dir=str(tmp_path / "alloc"),
                       options={"driver.raw_exec.enable": "true"})
    client = Client(cfg, InProcServerChannel(srv))
    client.start()
    yield srv, client, cfg
    client.shutdown()
    srv.shutdown()


class TestClientEndToEnd:
    def test_node_registration(self, dev_cluster):
        srv, client, cfg = dev_cluster
        node = srv.state.node_by_id(client.node.ID)
        assert node is not None
        assert node.Status == NodeStatusReady
        assert node.Attributes["driver.raw_exec"] == "1"
        assert node.ComputedClass

    def test_batch_job_runs_to_completion(self, dev_cluster):
        srv, client, cfg = dev_cluster
        job = parse_job('''
job "write" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    count = 2
    task "t" {
      driver = "raw_exec"
      config {
        command = "/bin/sh"
        args = ["-c", "echo done > ${NOMAD_TASK_DIR}/out.txt"]
      }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}''')
        srv.job_register(job)
        assert wait_for(lambda: (
            (allocs := srv.state.allocs_by_job("write"))
            and all(a.ClientStatus == "complete" for a in allocs)))
        allocs = srv.state.allocs_by_job("write")
        for a in allocs:
            out = os.path.join(cfg.alloc_dir, a.ID, "t", "local", "out.txt")
            assert os.path.exists(out)
            assert a.TaskStates["t"].State == "dead"
            assert a.TaskStates["t"].successful()
        assert srv.state.job_by_id("write").Status == "dead"

    def test_mock_driver_accepts_hcl_duration_config(self, dev_cluster):
        """Regression: HCL hands duration strings ("2s") through to driver
        config; the mock driver must parse them, not crash in restart
        backoff forever."""
        srv, client, cfg = dev_cluster
        job = parse_job('''
job "mocked" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    task "t" {
      driver = "mock_driver"
      config { run_for = "100ms" }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}''')
        srv.job_register(job)
        assert wait_for(lambda: (
            (allocs := srv.state.allocs_by_job("mocked"))
            and all(a.ClientStatus == "complete" for a in allocs)))

    def test_service_task_restarts_on_failure(self, dev_cluster):
        srv, client, cfg = dev_cluster
        job = parse_job('''
job "flaky" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    restart { attempts = 1 interval = "5m" delay = "1s" mode = "fail" }
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/false" }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}''')
        srv.job_register(job)
        assert wait_for(lambda: (
            (allocs := srv.state.allocs_by_job("flaky"))
            and any(a.ClientStatus == "failed" for a in allocs)), timeout=40)
        alloc = srv.state.allocs_by_job("flaky")[0]
        events = [e.Type for e in alloc.TaskStates["t"].Events]
        assert "Restarting" in events  # one restart attempt
        assert "Terminated" in events

    def test_stop_kills_running_task(self, dev_cluster):
        srv, client, cfg = dev_cluster
        job = parse_job('''
job "sleeper" {
  datacenters = ["dc1"]
  type = "service"
  group "g" {
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sleep" args = ["300"] }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}''')
        srv.job_register(job)
        assert wait_for(lambda: any(
            a.ClientStatus == "running"
            for a in srv.state.allocs_by_job("sleeper")))
        srv.job_deregister("sleeper")
        assert wait_for(lambda: all(
            a.ClientStatus in ("complete", "failed")
            for a in srv.state.allocs_by_job("sleeper")), timeout=30)


class TestDriverConfigSchemas:
    """Driver config maps validate against per-driver field schemas
    (reference: helper/fields/type.go FieldSchema maps used by each
    driver's Validate, e.g. client/driver/docker.go:116-140). Unknown
    keys are rejected — a typo'd key must fail loudly, not silently
    no-op at runtime."""

    def _driver(self, name):
        from nomad_tpu.client.driver import new_driver
        from nomad_tpu.client.driver.base import DriverContext

        return new_driver(name, DriverContext())

    def test_unknown_key_rejected_per_driver(self):
        import pytest as _pytest

        cases = {
            "docker": {"image": "redis", "imge_pull": True},
            "exec": {"command": "/bin/true", "comand": "x"},
            "raw_exec": {"command": "/bin/true", "arg": []},
            "java": {"jar_path": "a.jar", "jvm_opts": []},
            "qemu": {"image_path": "a.img", "portmap": {}},
            "mock_driver": {"run_for": 1, "runfor": 2},
        }
        for name, cfg in cases.items():
            with _pytest.raises(ValueError, match="unknown config key"):
                self._driver(name).validate(cfg)

    def test_reference_docker_keys_accepted_with_warning(self, caplog):
        """Reference-valid docker keys this driver does not implement
        (privileged, dns_servers, hostname, ...) validate — reference job
        specs stay portable — with a warning that they are ignored
        (reference field map: client/driver/docker.go:167-226)."""
        import logging

        from nomad_tpu.client.driver import base as _base

        _base._WARNED_IGNORED.clear()  # once-per-process memo
        with caplog.at_level(logging.WARNING, logger="nomad.driver"):
            self._driver("docker").validate({
                "image": "redis:3.2", "privileged": True,
                "dns_servers": ["8.8.8.8"], "hostname": "cache",
                "shm_size": 64, "ipc_mode": "host"})
        ignored = [r for r in caplog.records if "ignored" in r.message]
        assert len(ignored) == 5
        # Type errors on unimplemented keys still fail loudly.
        import pytest as _pytest

        with _pytest.raises(ValueError, match="privileged"):
            self._driver("docker").validate(
                {"image": "redis", "privileged": "yes-please"})

    def test_required_keys_enforced(self):
        import pytest as _pytest

        for name, key in (("docker", "image"), ("exec", "command"),
                          ("raw_exec", "command"), ("java", "jar_path"),
                          ("qemu", "image_path")):
            with _pytest.raises(ValueError, match=key):
                self._driver(name).validate({})

    def test_weak_typing_matches_hcl_decode(self):
        # HCL frontends hand over strings for scalars and single-element
        # lists of maps for blocks (reference: WeaklyTypedInput +
        # port_map block decoding).
        self._driver("docker").validate({
            "image": "redis:3.2", "args": ["-p", "6379"],
            "port_map": [{"db": 6379}], "network_mode": "host"})
        self._driver("qemu").validate({
            "image_path": "linux.img", "port_map": {"ssh": 22}})
        self._driver("mock_driver").validate({
            "run_for": "2s", "exit_code": "1"})

    def test_type_mismatch_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="must be a list"):
            self._driver("exec").validate(
                {"command": "/bin/true", "args": "not-a-list"})
        with _pytest.raises(ValueError, match="must be a int"):
            self._driver("mock_driver").validate({"exit_code": "NaN"})
