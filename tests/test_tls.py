"""TLS on the single-port RPC mux (reference: nomad/rpc.go:25-30 rpcTLS
byte + handleConn:88-132; TLSConfig in nomad/config.go): a mutual-TLS
cluster forms, replicates, and schedules; plaintext connections are refused
when verify_incoming is set; wrong-CA clients are rejected.
"""

import os
import subprocess
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.rpc.pool import ConnPool, ConnError, RPCError
from nomad_tpu.rpc.tls import TLSConfig
from nomad_tpu.server.server import ServerConfig
from nomad_tpu.structs import to_dict
from nomad_tpu.structs.structs import EvalStatusComplete

from helpers import wait_for  # noqa: E402


FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)


def make_ca(dirpath, name="ca"):
    """Self-signed CA + a cert it signs, via openssl."""
    ca_key = os.path.join(dirpath, f"{name}.key")
    ca_crt = os.path.join(dirpath, f"{name}.crt")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", ca_key, "-out", ca_crt, "-days", "1",
         "-subj", f"/CN=nomad-test-{name}"],
        check=True, capture_output=True)
    return ca_key, ca_crt


def issue_cert(dirpath, ca_key, ca_crt, name):
    key = os.path.join(dirpath, f"{name}.key")
    csr = os.path.join(dirpath, f"{name}.csr")
    crt = os.path.join(dirpath, f"{name}.crt")
    subprocess.run(
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", csr, "-subj", f"/CN={name}"],
        check=True, capture_output=True)
    subprocess.run(
        ["openssl", "x509", "-req", "-in", csr, "-CA", ca_crt,
         "-CAkey", ca_key, "-CAcreateserial", "-out", crt, "-days", "1"],
        check=True, capture_output=True)
    return key, crt


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("pki"))
    ca_key, ca_crt = make_ca(d)
    key, crt = issue_cert(d, ca_key, ca_crt, "server")
    evil_ca_key, evil_ca_crt = make_ca(d, "evil")
    evil_key, evil_crt = issue_cert(d, evil_ca_key, evil_ca_crt,
                                    "evil-client")
    return {"ca": ca_crt, "key": key, "crt": crt,
            "evil_ca": evil_ca_crt, "evil_key": evil_key,
            "evil_crt": evil_crt}


def tls_cfg(pki):
    return TLSConfig(enable_rpc=True, ca_file=pki["ca"],
                     cert_file=pki["crt"], key_file=pki["key"],
                     verify_incoming=True)


def leader_of(nodes):
    for n in nodes:
        if n.server.is_leader() and n.server._leader:
            return n
    return None


class TestTLSCluster:
    def test_mutual_tls_cluster_schedules(self, pki):
        """3 servers, every RPC and raft stream over mutual TLS: leadership
        establishes, a job registers through a follower, and its eval
        completes with allocations committed."""
        cfgs = [ServerConfig(num_schedulers=1) for _ in range(3)]
        nodes = [ClusterServer(cfg, tls=tls_cfg(pki)) for cfg in cfgs]
        addrs = [cs.addr for cs in nodes]
        for cs in nodes:
            cs.connect(list(addrs), raft_config=FAST)
        for cs in nodes:
            cs.start()
        try:
            assert wait_for(lambda: leader_of(nodes) is not None,
                            timeout=30)
            # Register through WHICHEVER node currently leads: the first
            # election of a fresh 3-node cluster can still be flapping
            # when the barrier above samples a momentary leader, and a
            # direct apply on a deposed node raises NotLeaderError.
            for _ in range(4):
                node = mock.node()
                deadline = time.monotonic() + 30
                while True:
                    ldr = leader_of(nodes)
                    try:
                        if ldr is not None:
                            ldr.server.node_register(node)
                            break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise
                    time.sleep(0.05)
            ldr = leader_of(nodes)
            follower = next(n for n in nodes if n is not ldr)
            job = mock.job()
            job.TaskGroups[0].Count = 2
            resp = follower.endpoints.handle("Job.Register",
                                             {"Job": to_dict(job)})
            eval_id = resp["EvalID"]
            assert wait_for(
                lambda: (l := leader_of(nodes)) is not None
                and (e := l.server.state.eval_by_id(
                    eval_id)) is not None
                and e.Status == EvalStatusComplete, timeout=60)
            # leader_of can flap to None between samples; the alloc read
            # rides the same None-safe retry as the eval wait.
            assert wait_for(
                lambda: (l := leader_of(nodes)) is not None
                and len(l.server.state.allocs_by_job(job.ID)) == 2,
                timeout=30)
        finally:
            for cs in nodes:
                cs.shutdown()

    def test_plaintext_refused_when_verify_incoming(self, pki):
        cfg = ServerConfig(num_schedulers=0)
        cs = ClusterServer(cfg, tls=tls_cfg(pki))
        cs.connect([cs.addr], raft_config=FAST)
        cs.start()
        try:
            assert wait_for(lambda: cs.server.is_leader()
                            and cs.server._leader, timeout=20)
            plain = ConnPool()  # no TLS context
            with pytest.raises((ConnError, OSError, TimeoutError,
                                RPCError)):
                plain.call(cs.addr, "Status.Ping", {}, timeout=2.0)
        finally:
            cs.shutdown()

    def test_wrong_ca_client_rejected(self, pki):
        from nomad_tpu.rpc.tls import client_context

        cfg = ServerConfig(num_schedulers=0)
        cs = ClusterServer(cfg, tls=tls_cfg(pki))
        cs.connect([cs.addr], raft_config=FAST)
        cs.start()
        try:
            assert wait_for(lambda: cs.server.is_leader()
                            and cs.server._leader, timeout=20)
            evil = ConnPool(tls_context=client_context(TLSConfig(
                enable_rpc=True, ca_file=pki["evil_ca"],
                cert_file=pki["evil_crt"], key_file=pki["evil_key"])))
            with pytest.raises((ConnError, OSError, TimeoutError,
                                RPCError)):
                evil.call(cs.addr, "Status.Ping", {}, timeout=2.0)
        finally:
            cs.shutdown()


class TestTLSGossipBootstrap:
    def test_gossip_bootstrapped_tls_cluster(self, pki):
        """The membership plane's RPC probes also ride TLS: a 3-server
        cluster forms via gossip bootstrap-expect with verify_incoming on
        (plaintext probes would be refused and the cluster could never
        elect)."""
        from nomad_tpu.gossip import GossipConfig

        def boot(name, join=None):
            cs = ClusterServer(ServerConfig(
                node_id="", num_schedulers=0, bootstrap_expect=3),
                tls=tls_cfg(pki))
            cs.connect([], raft_config=FAST)
            cs.start()
            cs.enable_gossip(name, join=join,
                             gossip_config=GossipConfig.fast())
            return cs

        nodes = [boot("t0")]
        ml = nodes[0].membership.memberlist
        seed = [f"{ml.addr}:{ml.port}"]
        nodes.append(boot("t1", join=seed))
        nodes.append(boot("t2", join=seed))
        try:
            assert wait_for(lambda: leader_of(nodes) is not None,
                            timeout=30)
            ldr = leader_of(nodes)
            assert wait_for(lambda: len(ldr.server.raft.peers) == 3,
                            timeout=20)
        finally:
            for cs in nodes:
                cs.shutdown()


class TestTLSAgentConfig:
    def test_tls_block_parses(self, tmp_path, pki):
        from nomad_tpu.agent.config import load_config_file

        p = tmp_path / "agent.hcl"
        p.write_text(f'''
region = "global"
tls {{
  rpc = true
  ca_file = "{pki['ca']}"
  cert_file = "{pki['crt']}"
  key_file = "{pki['key']}"
  verify_incoming = true
}}
''')
        cfg = load_config_file(str(p))
        assert cfg.tls_enable_rpc is True
        assert cfg.tls_ca_file == pki["ca"]
        assert cfg.tls_verify_incoming is True
