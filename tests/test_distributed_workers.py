"""Distributed scheduling workers: followers dequeue evaluations and submit
plans over leader RPC, so every server's CPU contributes scheduling
throughput (reference shapes: nomad/worker.go:101-130 workers on every
server, plan_endpoint.go:16 Plan.Submit, eval_endpoint.go:68 Eval.Dequeue,
leader.go:110-116 leader worker pausing)."""


import pytest

from nomad_tpu import mock
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.rpc.pool import RPCError
from nomad_tpu.server.server import ServerConfig
from nomad_tpu.structs import Plan, to_dict
from nomad_tpu.structs.structs import EvalStatusComplete


from helpers import wait_for  # noqa: E402


FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)


def make_cluster(n=3, num_schedulers=1):
    nodes = [ClusterServer(ServerConfig(num_schedulers=num_schedulers))
             for _ in range(n)]
    addrs = [cs.addr for cs in nodes]
    for cs in nodes:
        cs.connect(list(addrs), raft_config=FAST)
    for cs in nodes:
        cs.start()
    return nodes


def leader_of(nodes):
    for cs in nodes:
        if cs.server.is_leader() and cs.server._leader:
            return cs
    return None


def shutdown_all(nodes):
    for cs in nodes:
        try:
            cs.shutdown()
        except Exception:
            pass


class TestDistributedWorkers:
    def test_every_server_runs_workers_leader_paused(self):
        nodes = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            for cs in nodes:
                assert len(cs.server.remote_workers) == 1
            # Leader's routed workers stand down; its pipelined workers own
            # its scheduling capacity. Followers' routed workers are live.
            assert leader.server.remote_workers[0]._paused.is_set()
            for cs in nodes:
                if cs is not leader:
                    assert not cs.server.remote_workers[0]._paused.is_set()
        finally:
            shutdown_all(nodes)

    def test_follower_workers_schedule_jobs_over_rpc(self):
        """With the leader's local workers stopped, scheduling still
        completes: follower workers dequeue over Eval.Dequeue, plan against
        their local replica, and commit through Plan.Submit."""
        nodes = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            # Amputate the leader's own scheduling capacity.
            for w in leader.server.workers:
                w.stop()
            leader.server.workers = []

            for _ in range(2):
                leader.server.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = leader.server.job_register(job)

            assert wait_for(lambda: (
                (e := leader.server.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete), timeout=30)
            assert len(leader.server.state.allocs_by_job(job.ID)) == 10
            # The placements replicate back to the followers that made them.
            for cs in nodes:
                assert wait_for(
                    lambda cs=cs: len(
                        cs.server.state.allocs_by_job(job.ID)) == 10)
        finally:
            shutdown_all(nodes)

    def test_plan_submit_enforces_eval_token_over_rpc(self):
        """A plan whose EvalToken does not match the broker's outstanding
        token is rejected by the applier — optimistic concurrency holds
        across the RPC boundary (reference: plan_apply.go token check)."""
        nodes = make_cluster(2)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            follower = [cs for cs in nodes if cs is not leader][0]
            node = mock.node()
            leader.server.node_register(node)
            plan = Plan(EvalID="no-such-eval", Priority=50,
                        EvalToken="bogus-token")
            alloc = mock.alloc()
            alloc.NodeID = node.ID
            plan.append_alloc(alloc)
            with pytest.raises(RPCError):
                follower.endpoints.handle("Plan.Submit",
                                          {"Plan": to_dict(plan)})
        finally:
            shutdown_all(nodes)

    def test_leadership_change_repoints_remote_workers(self):
        """After the leader dies, follower workers re-aim at the new leader
        and keep scheduling; the new leader's routed workers pause."""
        nodes = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            for _ in range(2):
                leader.server.node_register(mock.node())
            leader.shutdown()
            rest = [cs for cs in nodes if cs is not leader]
            assert wait_for(lambda: leader_of(rest) is not None, timeout=30)
            new_leader = leader_of(rest)
            assert wait_for(
                lambda: new_leader.server.remote_workers[0]._paused.is_set())
            for w in new_leader.server.workers:
                w.stop()
            new_leader.server.workers = []
            # Fresh capacity + a job through the new leader, scheduled by
            # the one remaining follower's routed worker.
            for _ in range(2):
                new_leader.server.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = new_leader.server.job_register(job)
            assert wait_for(lambda: (
                (e := new_leader.server.state.eval_by_id(eval_id))
                is not None and e.Status == EvalStatusComplete), timeout=30)
            assert len(new_leader.server.state.allocs_by_job(job.ID)) == 10
        finally:
            shutdown_all(nodes)


class TestShutdownHygiene:
    """Round-3 regression class: daemon threads (workers, plan applier,
    raft loops) left inside an XLA dispatch at interpreter exit abort
    CPython/XLA teardown (bench rc=134). Server.shutdown() must join every
    JAX-touching thread before returning."""

    def test_shutdown_joins_all_server_threads(self):
        import threading

        # Only judge threads THIS test creates: an earlier test in the
        # process may have legitimately leaked past its own join timeout.
        preexisting = set(threading.enumerate())
        nodes = make_cluster(n=3, num_schedulers=1)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            # Put real scheduling work through so worker threads have
            # actually dispatched device work before we tear down.
            for _ in range(2):
                leader.server.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = leader.server.job_register(job)
            assert wait_for(lambda: (
                (e := leader.server.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete), timeout=30)
        finally:
            shutdown_all(nodes)
        # Every framework thread must be gone (or never started). Daemon
        # helpers that idle forever by design (timer wheel pool) are
        # exempt; worker/plan-apply/raft threads are not.
        deadline_names = ("worker", "remote-worker", "plan-apply",
                          "plan-eval", "raft-tick", "raft-apply",
                          "raft-notify", "raft-repl", "pipelined",
                          "alloc-update-flush")
        leftovers = [t.name for t in threading.enumerate()
                     if t not in preexisting
                     and any(t.name.startswith(p) for p in deadline_names)]
        assert not leftovers, f"threads survived shutdown: {leftovers}"
