"""QoS subsystem: tiered broker lanes, queue-age carry, deadline-aware
window sizing, admission control, and the disabled-mode equivalence gate
(ISSUE 8). Preemption has its own file (test_qos_preemption.py)."""

import io
import threading
import time
import urllib.error

import pytest

from nomad_tpu import mock
from nomad_tpu.qos import (
    AdmissionController,
    QoSBackpressureError,
    QoSConfig,
    QoSCounters,
    TIER_HIGH,
    TIER_LOW,
    TIER_NORMAL,
)
from nomad_tpu.resilience import failpoints
from nomad_tpu.server.blocked_evals import BlockedEvals
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import Evaluation, compute_node_class
from nomad_tpu.structs.structs import (
    EvalStatusComplete,
    EvalStatusPending,
    JobTypeService,
)

from helpers import wait_for  # noqa: E402


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


def make_eval(eid, priority, job_id=None, create_index=0):
    return Evaluation(ID=eid, Priority=priority, Type=JobTypeService,
                      TriggeredBy="job-register", JobID=job_id or eid,
                      Status=EvalStatusPending, CreateIndex=create_index)


def enabled_broker(**kw):
    qos = QoSConfig(enabled=True, **kw)
    broker = EvalBroker(qos=qos)
    broker.set_enabled(True)
    return broker, qos


class TestTierModel:
    def test_tier_mapping(self):
        qos = QoSConfig(enabled=True)
        assert qos.tier_of(100) == TIER_HIGH
        assert qos.tier_of(70) == TIER_HIGH
        assert qos.tier_of(50) == TIER_NORMAL
        assert qos.tier_of(30) == TIER_LOW
        assert qos.tier_of(1) == TIER_LOW
        # Core evals (priority 200) land high.
        assert qos.tier_of(200) == TIER_HIGH

    def test_window_fill_scales_with_budget(self):
        qos = QoSConfig(enabled=True, deadlines_s=(1.0, 4.0, 16.0))
        # Fresh eval: full window, full linger.
        count, fill = qos.window_fill(0.0, 90, 31, 0.002)
        assert count == 31 and fill == 0.002
        # Half the budget gone: window shrinks.
        count, fill = qos.window_fill(0.5, 90, 31, 0.002)
        assert 1 <= count <= 16
        # Budget blown: smallest useful window, no linger.
        count, fill = qos.window_fill(2.0, 90, 31, 0.002)
        assert count == max(1, 31 // 8) and fill == 0.0

    def test_window_one_never_batch_fills_under_qos(self):
        # Review regression: scheduler_window=1 means "no batch fill";
        # window_fill's max(1, ...) floor must not resurrect a fill of 1.
        from nomad_tpu.server.pipelined_worker import PipelinedWorker
        from nomad_tpu.server.fsm import FSM, DevRaft
        from nomad_tpu.tensor import TensorIndex

        broker = EvalBroker(qos=QoSConfig(enabled=True))
        broker.set_enabled(True)
        fsm = FSM()
        w = PipelinedWorker(DevRaft(fsm), broker, None, None,
                            TensorIndex(), window=1)
        w.qos = QoSConfig(enabled=True)
        broker.enqueue(make_eval("extra", 90))
        assert w._fill_window(make_eval("first", 90)) == []
        assert broker.stats.TotalReady == 1  # the extra eval stayed queued

    def test_window_fill_near_fresh_keeps_full_window(self):
        # Regression: int() flooring reported a 1-eval "cut" on every
        # healthy window (age of a few ms), poisoning window_cuts.
        qos = QoSConfig(enabled=True)
        count, _ = qos.window_fill(0.003, 90, 31, 0.002)
        assert count == 31


class TestTieredBroker:
    def test_high_tier_drains_first(self):
        broker, _ = enabled_broker()
        for i in range(10):
            broker.enqueue(make_eval(f"low{i}", 10, create_index=i))
        for i in range(3):
            broker.enqueue(make_eval(f"high{i}", 90, create_index=100 + i))
        got = [broker.dequeue(["service"], timeout=1)[0].ID
               for _ in range(5)]
        assert got[:3] == ["high0", "high1", "high2"], got
        assert all(g.startswith("low") for g in got[3:])

    def test_saturating_high_cannot_starve_low(self):
        # Continuous high-tier arrivals; an aged low eval must still be
        # served (promotion one tier per aging_s).
        broker, _ = enabled_broker(aging_s=0.05)
        broker.enqueue(make_eval("low", 10))
        time.sleep(0.16)  # ages past 2 * aging_s -> effective high tier
        served_low_at = None
        for i in range(20):
            broker.enqueue(make_eval(f"high{i}", 90, create_index=i))
            ev, token = broker.dequeue(["service"], timeout=1)
            broker.ack(ev.ID, token)
            if ev.ID == "low":
                served_low_at = i
                break
        assert served_low_at is not None, "aged low eval never served"
        assert broker.tier_promotions() >= 1

    def test_window_dequeue_orders_tiers(self):
        broker, _ = enabled_broker()
        for i in range(4):
            broker.enqueue(make_eval(f"low{i}", 10, create_index=i))
        broker.enqueue(make_eval("high", 90, create_index=50))
        window = broker.dequeue_window(["service"], 5, timeout=1,
                                       fill_timeout=0.01)
        assert window[0][0].ID == "high"
        assert len(window) == 5

    def test_disabled_broker_matches_legacy_ordering(self):
        # qos=None and QoSConfig(enabled=False) must produce the exact
        # pre-QoS ordering: priority desc, then CreateIndex asc.
        for qos in (None, QoSConfig(enabled=False)):
            broker = EvalBroker(qos=qos)
            broker.set_enabled(True)
            broker.enqueue(make_eval("b", 50, create_index=2))
            broker.enqueue(make_eval("a", 50, create_index=1))
            broker.enqueue(make_eval("c", 80, create_index=3))
            got = [broker.dequeue(["service"], timeout=1)[0].ID
                   for _ in range(3)]
            assert got == ["c", "a", "b"], (qos, got)

    def test_tier_depths_and_qos_stats(self):
        broker, _ = enabled_broker()
        broker.enqueue(make_eval("l", 10))
        broker.enqueue(make_eval("n", 50))
        broker.enqueue(make_eval("h", 90))
        assert broker.tier_depths() == [1, 1, 1]
        stats = broker.qos_stats()
        assert stats["TierDepths"] == {"high": 1, "normal": 1, "low": 1}
        assert set(stats["SLOBurn"]) == {"high", "normal", "low"}

    def test_slo_burn_records_deadline_misses(self):
        broker, qos = enabled_broker(deadlines_s=(0.0, 0.0, 0.0))
        broker.enqueue(make_eval("h", 90))
        ev, token = broker.dequeue(["service"], timeout=1)
        time.sleep(0.01)  # any wait > 0.0 deadline = a miss
        broker.ack(ev.ID, token)
        assert broker.slo_burn()[TIER_HIGH] == 1.0


class TestQueueAgeCarry:
    """Satellite regression (ISSUE 8): Nack and blocked-eval requeues
    must carry the ORIGINAL enqueue time — a requeued eval re-entering
    with a reset age would park behind fresh arrivals forever."""

    def test_nack_preserves_first_enqueue_time(self):
        broker, _ = enabled_broker(aging_s=1000.0)
        broker.enqueue(make_eval("old", 10))
        first = broker.queue_age("old")
        assert first is not None
        ev, token = broker.dequeue(["service"], timeout=1)
        time.sleep(0.02)
        broker.nack(ev.ID, token)
        # Age survives the redelivery cycle bit-identical.
        assert broker.queue_age("old") == first

    def test_requeued_eval_outranks_fresh_same_tier_arrival(self):
        broker, _ = enabled_broker(aging_s=1000.0)
        broker.enqueue(make_eval("old", 10, create_index=1))
        ev, token = broker.dequeue(["service"], timeout=1)
        broker.nack(ev.ID, token)
        broker.enqueue(make_eval("fresh", 10, create_index=2))
        got, _ = broker.dequeue(["service"], timeout=1)
        assert got.ID == "old"

    def test_aged_nacked_eval_promotes_from_original_time(self):
        # The aging clock must run from FIRST enqueue, not the requeue:
        # after a nack the low eval still outranks a fresh high arrival
        # once its total queue time crosses the promotion threshold.
        broker, _ = enabled_broker(aging_s=0.04)
        broker.enqueue(make_eval("low", 10))
        ev, token = broker.dequeue(["service"], timeout=1)
        time.sleep(0.13)  # > 3 * aging_s, all spent before the requeue
        broker.nack(ev.ID, token)
        broker.enqueue(make_eval("high", 90))
        got, _ = broker.dequeue(["service"], timeout=1)
        assert got.ID == "low", "requeue reset the aging clock"

    def test_token_gated_deferred_requeue_keeps_age(self):
        # Review regression: a scheduler reblocking its own eval defers
        # it behind the outstanding token (_requeue); the ack that then
        # re-enqueues it must hand back the ORIGINAL first-enqueue time,
        # not a fresh one.
        broker, _ = enabled_broker(aging_s=1000.0)
        broker.enqueue(make_eval("e", 50))
        first = broker.queue_age("e")
        ev, token = broker.dequeue(["service"], timeout=1)
        time.sleep(0.02)
        # Token-gated requeue while still outstanding -> deferred.
        broker.enqueue_all({"e": (ev, token)}, ages={"e": first})
        broker.ack(ev.ID, token)  # releases the deferred requeue
        assert broker.stats.TotalReady == 1
        assert broker.queue_age("e") == first, \
            "deferred requeue reset the aging clock"

    def test_ack_drops_age_entry(self):
        broker, _ = enabled_broker()
        broker.enqueue(make_eval("e", 50))
        ev, token = broker.dequeue(["service"], timeout=1)
        broker.ack(ev.ID, token)
        assert broker.queue_age("e") is None

    def test_blocked_requeue_carries_age(self):
        broker, _ = enabled_broker(aging_s=1000.0)
        blocked = BlockedEvals(broker)
        blocked.set_enabled(True)
        try:
            broker.enqueue(make_eval("e", 50))
            first = broker.queue_age("e")
            ev, token = broker.dequeue(["service"], timeout=1)
            # Scheduler couldn't place: the eval blocks (same ID, token),
            # then capacity arrives and it requeues.
            reblocked = ev.copy()
            reblocked.Status = "blocked"
            reblocked.SnapshotIndex = 100
            blocked.reblock(reblocked, token)
            broker.ack(ev.ID, token)  # original delivery resolved
            blocked.unblock("class-a", 200)
            assert wait_for(lambda: broker.stats.TotalReady >= 1,
                            timeout=5, interval=0.01)
            assert broker.queue_age("e") == pytest.approx(first)
        finally:
            blocked.set_enabled(False)

    def test_new_blocked_eval_inherits_parent_age(self):
        broker, _ = enabled_broker(aging_s=1000.0)
        blocked = BlockedEvals(broker)
        blocked.set_enabled(True)
        try:
            broker.enqueue(make_eval("parent", 50))
            first = broker.queue_age("parent")
            ev, token = broker.dequeue(["service"], timeout=1)
            child = ev.create_blocked_eval({}, False)
            child.SnapshotIndex = 100
            blocked.block(child)
            broker.ack(ev.ID, token)
            blocked.unblock("class-a", 200)
            assert wait_for(lambda: broker.stats.TotalReady >= 1,
                            timeout=5, interval=0.01)
            assert broker.queue_age(child.ID) == pytest.approx(first)
        finally:
            blocked.set_enabled(False)


class TestAdmission:
    def _controller(self, broker, qos):
        return AdmissionController(qos, broker, QoSCounters())

    def test_depth_shed_low_tier_only(self):
        broker, qos = enabled_broker(admit_depth=(0, 8192, 1))
        ctl = self._controller(broker, qos)
        broker.enqueue(make_eval("l", 10))
        with pytest.raises(QoSBackpressureError):
            ctl.admit(10)
        # High tier is unlimited by default.
        ctl.admit(90)
        assert ctl.counters.snapshot()["shed"] == 1
        assert ctl.counters.snapshot()["admitted"] == 1

    def test_burn_shed_protects_higher_tier(self):
        broker, qos = enabled_broker(deadlines_s=(0.0, 0.0, 0.0),
                                     burn_shed=0.5)
        ctl = self._controller(broker, qos)
        # Make the high tier burn: one completion over deadline...
        broker.enqueue(make_eval("h", 90))
        ev, token = broker.dequeue(["service"], timeout=1)
        time.sleep(0.01)
        broker.ack(ev.ID, token)
        # ...and keep high backlog non-empty (burn only sheds while the
        # protected tier actually has queued work).
        broker.enqueue(make_eval("h2", 90))
        with pytest.raises(QoSBackpressureError):
            ctl.admit(10)
        ctl.admit(90)  # high itself never burn-shed

    def test_disabled_is_noop(self):
        broker = EvalBroker()
        ctl = AdmissionController(None, broker, QoSCounters())
        ctl.admit(1)  # no broker introspection, no error
        ctl2 = AdmissionController(QoSConfig(enabled=False), broker,
                                   QoSCounters())
        ctl2.admit(1)

    def test_admission_failpoint_drop_forces_shed(self):
        broker, qos = enabled_broker()
        ctl = self._controller(broker, qos)
        failpoints.arm_from_spec("broker.admission=drop:count=1")
        with pytest.raises(QoSBackpressureError):
            ctl.admit(90)
        ctl.admit(90)  # healed after count

    def test_admission_failpoint_error_surfaces(self):
        broker, qos = enabled_broker()
        ctl = self._controller(broker, qos)
        failpoints.arm_from_spec("broker.admission=error:count=1")
        with pytest.raises(failpoints.FailpointError):
            ctl.admit(90)


class TestServerIngress:
    def _server(self, **qos_kw):
        srv = Server(ServerConfig(num_schedulers=0,
                                  qos=QoSConfig(enabled=True, **qos_kw),
                                  min_heartbeat_ttl=24 * 3600.0,
                                  heartbeat_grace=24 * 3600.0))
        srv.establish_leadership()
        return srv

    @staticmethod
    def _job(priority):
        job = mock.job()
        job.Priority = priority
        task = job.TaskGroups[0].Tasks[0]
        task.Resources.Networks = []
        task.Services = []
        if task.LogConfig is not None:
            task.LogConfig.MaxFiles = 1
            task.LogConfig.MaxFileSizeMB = 1
        return job

    def test_register_shed_is_typed_and_preserves_state(self):
        srv = self._server(admit_depth=(0, 8192, 1))
        try:
            node = mock.node()
            compute_node_class(node)
            srv.node_register(node)
            srv.job_register(self._job(10))  # fills the low lane
            jobs_index = srv.state.get_index("jobs")
            with pytest.raises(QoSBackpressureError):
                srv.job_register(self._job(10))
            # Shed BEFORE any write: no job, no eval landed.
            assert srv.state.get_index("jobs") == jobs_index
        finally:
            srv.shutdown()

    def test_shed_crosses_rpc_with_remote_type(self):
        # Over the wire the typed error must keep its class name so
        # clients/forwarders can react (rpc _err_string contract).
        from nomad_tpu.rpc.pool import RPCError
        srv = self._server(admit_depth=(0, 8192, 1))
        try:
            srv.job_register(self._job(10))
            try:
                srv.job_register(self._job(10))
            except QoSBackpressureError as exc:
                wire = f"{type(exc).__name__}: {exc}"
                assert RPCError(wire).remote_type == "QoSBackpressureError"
        finally:
            srv.shutdown()

    def test_internal_triggers_bypass_admission(self):
        from nomad_tpu.structs.structs import EvalTriggerPeriodicJob
        srv = self._server(admit_depth=(0, 8192, 1))
        try:
            srv.job_register(self._job(10))
            # A periodic child launch must never shed.
            srv.job_register(self._job(10),
                             trigger=EvalTriggerPeriodicJob)
        finally:
            srv.shutdown()


class TestClientBackpressureRetry:
    def test_client_retries_429_with_policy(self, monkeypatch):
        from nomad_tpu.api import client as api_client

        calls = {"n": 0}

        class _Resp:
            headers = {"X-Nomad-Index": "7"}

            def read(self):
                return b'{"EvalID": "ok"}'

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        def fake_urlopen(req, timeout=None):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise urllib.error.HTTPError(
                    "http://x/v1/jobs", 429, "Too Many Requests", {},
                    io.BytesIO(b"submission shed (low tier)"))
            return _Resp()

        monkeypatch.setattr(api_client.urllib.request, "urlopen",
                            fake_urlopen)
        c = api_client.Client(backpressure_retries=4)
        out, meta = c.put("/v1/jobs", {"Job": {}})
        assert out["EvalID"] == "ok"
        assert calls["n"] == 3  # two sheds retried, third landed

    def test_client_backpressure_budget_exhausts_typed(self, monkeypatch):
        from nomad_tpu.api import client as api_client

        def always_429(req, timeout=None):
            raise urllib.error.HTTPError(
                "http://x/v1/jobs", 429, "Too Many Requests", {},
                io.BytesIO(b"shed"))

        monkeypatch.setattr(api_client.urllib.request, "urlopen",
                            always_429)
        c = api_client.Client(backpressure_retries=2)
        with pytest.raises(api_client.BackpressureAPIError):
            c.put("/v1/jobs", {"Job": {}})


def _build_fleet(n):
    """Deterministic fleet: stable IDs and strictly distinct capacities so
    binpack scores differ by far more than the tie-break noise (<=1e-3)
    and placement argmaxes are reproducible run-to-run."""
    nodes = []
    for i in range(n):
        node = mock.node()
        node.ID = f"node-{i:03d}"
        node.Name = f"node-{i:03d}"
        node.Resources.CPU = 4000 + 100 * i
        node.Reserved = None
        compute_node_class(node)
        nodes.append(node)
    return nodes


def _storm_job(prio, jid):
    job = mock.job()
    job.ID = jid
    job.Name = jid
    job.Priority = prio
    tg = job.TaskGroups[0]
    tg.Count = 3
    task = tg.Tasks[0]
    task.Resources.CPU = 100
    task.Resources.MemoryMB = 32
    task.Resources.DiskMB = 10
    task.Resources.Networks = []
    task.Services = []
    if task.LogConfig is not None:
        task.LogConfig.MaxFiles = 1
        task.LogConfig.MaxFileSizeMB = 1
    return job


def _run_storm_sync(qos):
    """Fixed-order mixed-priority storm, processed SYNCHRONOUSLY by one
    worker (no live worker threads -> no timing nondeterminism): register
    everything, then drain the broker one eval at a time. Returns
    {alloc.Name: NodeID} plus the completion order of eval job ids."""
    from nomad_tpu.server.worker import Worker

    srv = Server(ServerConfig(num_schedulers=0, qos=qos,
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in _build_fleet(12):
            srv.node_register(node)
        eval_of = {}
        for i in range(8):
            eval_of[srv.job_register(_storm_job(10, f"low-{i}"))[0]] = \
                f"low-{i}"
        for i in range(2):
            eval_of[srv.job_register(_storm_job(90, f"high-{i}"))[0]] = \
                f"high-{i}"
        w = Worker(srv.raft, srv.eval_broker, srv.plan_queue,
                   srv.blocked_evals, srv.tindex)
        w.qos = srv.qos
        w.qos_counters = srv.qos_counters
        order = []
        before = {eid: (srv.state.eval_by_id(eid) or Evaluation()).Status
                  for eid in eval_of}
        for _ in range(len(eval_of) * 3):
            if not w.process_one(timeout=0.05):
                break
            for eid, jid in eval_of.items():
                e = srv.state.eval_by_id(eid)
                if (e is not None and e.Status == EvalStatusComplete
                        and before[eid] != EvalStatusComplete):
                    before[eid] = EvalStatusComplete
                    order.append(jid)
        placements = {}
        for eid in eval_of:
            e = srv.state.eval_by_id(eid)
            assert e is not None and e.Status == EvalStatusComplete, \
                (eval_of[eid], e)
            for a in srv.state.allocs_by_eval(eid):
                placements[a.Name] = a.NodeID
        return placements, order
    finally:
        srv.shutdown()


class TestEquivalenceGate:
    """ISSUE 8 satellite: fixed-seed mixed-priority storm — identical
    placements run-to-run, QoS-disabled mode placement-identical to the
    FIFO path, high tier first under QoS."""

    def test_disabled_mode_identical_to_fifo_path(self):
        fifo, order_fifo = _run_storm_sync(None)
        off, order_off = _run_storm_sync(QoSConfig(enabled=False))
        assert fifo == off
        assert order_fifo == order_off

    def test_identical_placements_run_to_run(self):
        a, order_a = _run_storm_sync(QoSConfig(enabled=True,
                                               aging_s=1000.0))
        b, order_b = _run_storm_sync(QoSConfig(enabled=True,
                                               aging_s=1000.0))
        assert a == b
        assert order_a == order_b

    def test_qos_serves_high_tier_first(self):
        # (The legacy heap is already priority-ordered — reference v0.4
        # semantics — so this holds in both modes; what QoS adds on top
        # is aging, deadline windows, admission, and preemption.)
        _, order = _run_storm_sync(QoSConfig(enabled=True,
                                             aging_s=1000.0))
        assert order[0].startswith("high-") \
            and order[1].startswith("high-"), order

    def test_qos_enabled_places_full_storm(self):
        placements, _ = _run_storm_sync(QoSConfig(enabled=True))
        assert len(placements) == 10 * 3  # every instance of every job
