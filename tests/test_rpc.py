"""Network RPC plane tests: framing, pooling, forwarding, blocking queries,
and a real multi-server cluster over TCP loopback with a wire-connected
client (reference shapes: nomad/rpc_test.go forwarding, pool behavior,
client/client_test.go booting a real client against a test server).
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.rpc import NetServerChannel, RpcProxy
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc import ConnPool, RPCError, RPCServer
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.server.server import ServerConfig
from nomad_tpu.structs import to_dict
from nomad_tpu.structs.structs import EvalStatusComplete


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked cluster suite: one retry

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)


@pytest.fixture
def cluster():
    nodes = [ClusterServer(ServerConfig(node_id="", num_schedulers=1))
             for _ in range(3)]
    addrs = [n.addr for n in nodes]
    for n in nodes:
        n.connect(addrs, raft_config=FAST)
    for n in nodes:
        n.start()
    assert wait_for(lambda: sum(
        1 for n in nodes if n.server.is_leader()) == 1)
    yield nodes
    for n in nodes:
        n.shutdown()


def leader_of(nodes):
    for n in nodes:
        if n.server.is_leader() and n.server._leader:
            return n
    return None


class TestWire:
    def test_echo_roundtrip(self):
        srv = RPCServer(rpc_handler=lambda m, b: {"method": m, "body": b})
        srv.start()
        pool = ConnPool()
        try:
            resp = pool.call(srv.addr, "Echo.Hello", {"x": 1})
            assert resp == {"method": "Echo.Hello", "body": {"x": 1}}
        finally:
            pool.close()
            srv.shutdown()

    def test_remote_error_propagates(self):
        def boom(method, body):
            raise ValueError("nope")

        srv = RPCServer(rpc_handler=boom)
        srv.start()
        pool = ConnPool()
        try:
            with pytest.raises(RPCError) as exc:
                pool.call(srv.addr, "X.Y", {})
            assert exc.value.remote_type == "ValueError"
        finally:
            pool.close()
            srv.shutdown()

    def test_concurrent_requests_multiplex(self):
        """Slow requests must not head-of-line block fast ones on the same
        connection (reference: per-request goroutines + yamux streams)."""
        def handler(method, body):
            if body["slow"]:
                time.sleep(0.5)
            return body["v"]

        srv = RPCServer(rpc_handler=handler)
        srv.start()
        pool = ConnPool()
        results = {}

        def call(i, slow):
            results[i] = pool.call(srv.addr, "M", {"v": i, "slow": slow})

        try:
            t_slow = threading.Thread(target=call, args=(0, True))
            t_slow.start()
            time.sleep(0.05)
            start = time.monotonic()
            call(1, False)
            fast_latency = time.monotonic() - start
            t_slow.join()
            assert results == {0: 0, 1: 1}
            assert fast_latency < 0.3  # didn't wait behind the slow one
        finally:
            pool.close()
            srv.shutdown()

    def test_pool_reconnects_after_server_restart(self):
        srv = RPCServer(rpc_handler=lambda m, b: "a")
        srv.start()
        addr = srv.addr
        host, port = addr.rsplit(":", 1)
        pool = ConnPool()
        try:
            assert pool.call(addr, "M", {}) == "a"
            srv.shutdown()
            srv2 = None
            for _ in range(100):  # old conn may pin the port briefly
                try:
                    srv2 = RPCServer(port=int(port),
                                     rpc_handler=lambda m, b: "b")
                    break
                except OSError:
                    time.sleep(0.1)
            assert srv2 is not None
            srv2.start()
            assert pool.call(addr, "M", {}) == "b"
        finally:
            pool.close()
            srv2.shutdown()


class TestClusterRPC:
    def test_write_on_follower_forwards_to_leader(self, cluster):
        leader = leader_of(cluster)
        follower = [n for n in cluster if n is not leader][0]
        pool = ConnPool()
        try:
            job = mock.job()
            resp = pool.call(follower.addr, "Job.Register",
                             {"Job": to_dict(job)})
            assert resp["EvalID"]
            # The write landed on the leader and replicated everywhere.
            for n in cluster:
                assert wait_for(
                    lambda n=n: n.server.state.job_by_id(job.ID) is not None)
        finally:
            pool.close()

    def test_status_endpoints(self, cluster):
        leader = leader_of(cluster)
        pool = ConnPool()
        try:
            assert pool.call(cluster[0].addr, "Status.Ping", {}) is True
            assert pool.call(cluster[0].addr, "Status.Leader",
                             {}) == leader.addr
            peers = pool.call(cluster[0].addr, "Status.Peers", {})
            assert sorted(peers) == sorted(n.addr for n in cluster)
        finally:
            pool.close()

    def test_blocking_query_fires_on_write(self, cluster):
        leader = leader_of(cluster)
        pool = ConnPool()
        try:
            # Seed one write so the table index is non-zero (index 0 means
            # "no blocking possible", mirroring the reference's index floor).
            pool.call(leader.addr, "Job.Register",
                      {"Job": to_dict(mock.job())})
            jobs = pool.call(leader.addr, "Job.List", {})
            index = jobs["Index"]
            assert index > 0
            result = {}

            def blocked():
                result["resp"] = pool.call(
                    leader.addr, "Job.List",
                    {"MinQueryIndex": index, "MaxQueryTime": 10.0})

            t = threading.Thread(target=blocked)
            t.start()
            time.sleep(0.3)
            assert t.is_alive()  # parked on the watch
            job = mock.job()
            pool.call(leader.addr, "Job.Register", {"Job": to_dict(job)})
            t.join(timeout=10)
            assert not t.is_alive()
            assert result["resp"]["Index"] > index
            assert any(j["ID"] == job.ID for j in result["resp"]["Jobs"])
        finally:
            pool.close()

    def test_region_mismatch_rejected_without_route(self, cluster):
        pool = ConnPool()
        try:
            with pytest.raises(RPCError) as exc:
                pool.call(cluster[0].addr, "Job.List", {"Region": "mars"})
            assert "NoRegionPathError" in str(exc.value)
        finally:
            pool.close()


class TestWireClient:
    def test_client_runs_job_over_network(self, cluster, tmp_path):
        """A real Client over real TCP against a 3-server raft cluster:
        register → heartbeat → watch → run task → status sync
        (reference: client/client_test.go against testServer)."""
        leader = leader_of(cluster)
        addrs = [n.addr for n in cluster]
        cfg = ClientConfig(
            state_dir=str(tmp_path / "state"),
            alloc_dir=str(tmp_path / "allocs"),
            node_class="", options={"driver.allowlist": "mock_driver"})
        channel = NetServerChannel(addrs)
        client = Client(cfg, channel)
        client.start()
        try:
            assert wait_for(lambda: (
                (n := leader.server.state.node_by_id(client.node.ID))
                is not None and n.Status == "ready"))
            job = mock.job()
            job.TaskGroups[0].Count = 2
            job.TaskGroups[0].Tasks[0].Driver = "mock_driver"
            job.TaskGroups[0].Tasks[0].Config = {"run_for": 0.2}
            pool = ConnPool()
            try:
                pool.call(addrs[0], "Job.Register", {"Job": to_dict(job)})
            finally:
                pool.close()
            # Client pulls allocs over the blocking query, runs them with
            # the mock driver, and syncs status back over the wire.
            # Generous: under full-suite load the scheduling round trip +
            # mock task execution can stretch well past the isolated-run
            # time (election jitter, GIL pressure from parallel compiles).
            assert wait_for(lambda: (
                (allocs := leader.server.state.allocs_by_job(job.ID))
                and len(allocs) == 2
                and all(a.ClientStatus in ("running", "complete")
                        for a in allocs)), timeout=60)
        finally:
            client.shutdown()


class TestRpcProxy:
    def test_failover_rotation(self):
        p = RpcProxy(["a:1", "b:2", "c:3"])
        assert p.find_server() == "a:1"
        p.notify_failed("a:1")
        assert p.find_server() == "b:2"
        assert p.servers() == ["b:2", "c:3", "a:1"]

    def test_update_keeps_order_of_survivors(self):
        p = RpcProxy(["a:1", "b:2"])
        p.notify_failed("a:1")          # b first now
        p.update(["a:1", "b:2", "c:3"])
        assert p.servers()[0] == "b:2"  # surviving order kept
        assert "c:3" in p.servers()
