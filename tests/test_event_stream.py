"""Event broker + /v1/event/stream + api client + CLI tests.

Broker unit coverage (ring/index semantics, the dev-mode sequencer,
slow-consumer drop-oldest, fan-out, reset), then black-box endpoint
coverage against a dev-mode agent (chunked frames, heartbeats, filters,
resume-from-index, the 416 gap contract), then the CLI rendering layer
over a canned stream. The failover chaos gate lives in
test_chaos_schedules.py; the state-equivalence oracle in
test_event_equivalence.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import Client as APIClient, EventGapAPIError
from nomad_tpu.events import (
    EventBroker,
    EventGapError,
    build_events,
    new_event,
)
from nomad_tpu.resilience import failpoints
from nomad_tpu.structs import to_dict

from helpers import wait_for  # noqa: E402


def ev(topic="Node", etype="NodeStatusUpdated", key="n1", payload=None):
    return new_event(topic, etype, key, payload or {"ID": key})


def batch_event(job_id="j1", alloc_ids=("a1", "a2", "a3"),
                node_ids=("n1", "n2"), counts=(2, 1)):
    return new_event("AllocationBatch", "AllocationBatchCommitted", job_id, {
        "JobID": job_id, "EvalID": "e1", "Kind": "system",
        "Count": len(alloc_ids), "AllocIDs": list(alloc_ids),
        "Names": [f"{job_id}.g[{i}]" for i in range(len(alloc_ids))],
        "RowNodeIDs": list(node_ids), "Counts": list(counts),
    })


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


class TestBroker:
    def test_replay_then_live_in_index_order(self):
        b = EventBroker(size=16)
        for i in range(1, 4):
            b.publish(i, [ev(key=f"n{i}")])
        sub = b.subscribe(from_index=0)
        b.publish(4, [ev(key="n4")])
        got = [sub.next(timeout=1) for _ in range(4)]
        assert [f["Index"] for f in got] == [1, 2, 3, 4]
        assert got[3]["Events"][0]["Key"] == "n4"
        assert sub.next(timeout=0.05) is None  # drained, not closed

    def test_resume_from_index_exact(self):
        """from_index is EXCLUSIVE (pass the last index you saw): the
        continuation neither duplicates it nor skips its successor."""
        b = EventBroker(size=16)
        for i in range(1, 6):
            b.publish(i, [ev(key=f"n{i}")])
        sub = b.subscribe(from_index=3)
        got = [sub.next(timeout=1) for _ in range(2)]
        assert [f["Index"] for f in got] == [4, 5]

    def test_gap_error_below_floor(self):
        b = EventBroker(size=2)
        for i in range(1, 6):
            b.publish(i, [ev(key=f"n{i}")])
        with pytest.raises(EventGapError) as exc:
            b.subscribe(from_index=1)
        assert exc.value.floor == 3  # ring holds 4,5; 3 was evicted last
        sub = b.subscribe(from_index=3)  # exactly at the floor is fine
        assert sub.next(timeout=1)["Index"] == 4

    def test_empty_batches_advance_coverage_without_slots(self):
        """Entries that publish no events still advance Tail — coverage
        over the log — but occupy no ring slots and raise no gap."""
        b = EventBroker(size=2)
        for i in range(1, 50):
            b.publish(i, [])
        assert b.stats()["Tail"] == 49
        assert b.stats()["Depth"] == 0
        sub = b.subscribe(from_index=0)  # no gap: nothing was evicted
        b.publish(50, [ev()])
        assert sub.next(timeout=1)["Index"] == 50

    def test_out_of_order_publish_held_for_predecessors(self):
        """The dev-mode sequencer: reservations taken in index order
        gate emission, so publishes arriving 3,1,2 stream as 1,2,3."""
        b = EventBroker(size=16)
        for i in (1, 2, 3):
            b.reserve(i)
        sub = b.subscribe(from_index=0)
        b.publish(3, [ev(key="n3")])
        assert sub.next(timeout=0.05) is None  # held: 1 and 2 in flight
        b.publish(1, [ev(key="n1")])
        assert sub.next(timeout=1)["Index"] == 1
        b.publish(2, [ev(key="n2")])
        got = [sub.next(timeout=1) for _ in range(2)]
        assert [f["Index"] for f in got] == [2, 3]

    def test_slow_consumer_drops_oldest_never_blocks(self):
        b = EventBroker(size=16)
        sub = b.subscribe(from_index=0, queue_size=2)
        for i in range(1, 6):
            b.publish(i, [ev(key=f"n{i}")])
        first = sub.next(timeout=1)
        assert first["Index"] == 4  # 1..3 dropped oldest-first
        assert first["Dropped"] == 3
        second = sub.next(timeout=1)
        assert second["Index"] == 5 and "Dropped" not in second
        assert sub.dropped_total == 3
        assert b.stats()["Dropped"] == 3

    def test_topic_and_key_filters(self):
        b = EventBroker(size=16)
        sub = b.subscribe(topics=["Job"], filters={"Job": ["j2"]})
        b.publish(1, [ev()])  # Node: filtered
        b.publish(2, [new_event("Job", "JobRegistered", "j1", {"ID": "j1"})])
        b.publish(3, [new_event("Job", "JobRegistered", "j2", {"ID": "j2"})])
        frame = sub.next(timeout=1)
        assert frame["Index"] == 3
        assert frame["Events"][0]["Key"] == "j2"

    def test_fanout_expands_batch_at_read_time(self):
        b = EventBroker(size=16)
        plain = b.subscribe(from_index=0)
        fan = b.subscribe(from_index=0, fanout=True)
        b.publish(1, [batch_event()])
        got = plain.next(timeout=1)["Events"]
        assert len(got) == 1 and got[0]["Type"] == "AllocationBatchCommitted"
        rows = fan.next(timeout=1)["Events"]
        assert [e["Type"] for e in rows] == ["AllocPlaced"] * 3
        # Row/count descriptor decodes to the per-alloc node mapping.
        assert [(e["Key"], e["Payload"]["NodeID"]) for e in rows] == [
            ("a1", "n1"), ("a2", "n1"), ("a3", "n2")]
        assert all(e["Index"] == 1 for e in rows)

    def test_reset_closes_subscribers_and_raises_floor(self):
        b = EventBroker(size=16)
        b.publish(1, [ev()])
        sub = b.subscribe(from_index=0)
        b.reset(10)
        closed, reason = sub.status()
        assert wait_for(lambda: sub.status()[0], timeout=1)
        assert "snapshot" in sub.status()[1]
        with pytest.raises(EventGapError):
            b.subscribe(from_index=5)
        sub2 = b.subscribe(from_index=10)  # resubscribe at the new floor
        b.publish(11, [ev(key="n11")])
        assert sub2.next(timeout=1)["Index"] == 11

    def test_schema_rejects_unknown_literals(self):
        with pytest.raises(ValueError):
            new_event("Bogus", "NodeRegistered", "k")
        with pytest.raises(ValueError):
            new_event("Node", "BogusType", "k")
        with pytest.raises(ValueError):
            new_event("Job", "NodeRegistered", "k")  # topic mismatch

    def test_publish_failpoint_drop_is_coverage_invisible(self):
        """events.publish drop: the batch is lost to subscribers but
        coverage still advances — no gap error, no FSM impact; only the
        equivalence fold can see the hole."""
        b = EventBroker(size=16)
        sub = b.subscribe(from_index=0)
        failpoints.arm_from_spec("events.publish=drop:count=1")
        b.publish(1, [ev(key="lost")])
        b.publish(2, [ev(key="kept")])
        frame = sub.next(timeout=1)
        assert frame["Index"] == 2
        assert frame["Events"][0]["Key"] == "kept"
        stats = b.stats()
        assert stats["Tail"] == 2 and stats["Published"] == 1

    def test_builders_cover_every_message_type(self):
        """Every FSM MessageType has a publish hook (or an explicit
        no-op): an unmapped type would silently hole the stream."""
        from nomad_tpu.events.builders import _BUILDERS
        from nomad_tpu.server.fsm import MessageType

        assert set(_BUILDERS) == {int(m) for m in MessageType}


# ------------------------------------------------------------- endpoint

@pytest.fixture(scope="module")
def event_agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.http_port = 0
    config.data_dir = str(tmp_path_factory.mktemp("event-agent"))
    agent = Agent(config)
    agent.start()
    api = APIClient(address=f"http://127.0.0.1:{agent.http.port}")
    yield agent, api
    agent.shutdown()


def _stream_url(agent, params=""):
    return (f"http://127.0.0.1:{agent.http.port}/v1/event/stream"
            + (f"?{params}" if params else ""))


class TestEventStreamEndpoint:
    def test_stream_replays_and_follows(self, event_agent):
        agent, api = event_agent
        node = mock.node()
        agent.rpc("Node.Register", {"Node": to_dict(node)})
        got = []
        done = threading.Event()

        def consume():
            stream = api.event_stream(from_index=0, heartbeat=0.5)
            for frame in stream:
                got.append(frame)
                if any(e["Type"] == "JobRegistered"
                       for e in frame["Events"]):
                    break
            stream.close()
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        job = mock.job()
        agent.rpc("Job.Register", {"Job": to_dict(job)})
        assert done.wait(15), "stream never delivered the registration"
        indexes = [f["Index"] for f in got]
        assert indexes == sorted(set(indexes)), "frames out of order"
        types = [e["Type"] for f in got for e in f["Events"]]
        assert "NodeRegistered" in types and "JobRegistered" in types

    def test_topic_filter_and_resume(self, event_agent):
        agent, api = event_agent
        job = mock.job()
        agent.rpc("Job.Register", {"Job": to_dict(job)})
        stream = api.event_stream(topics=["Job"], from_index=0,
                                  heartbeat=0.5)
        frame = next(stream)
        stream.close()
        assert all(e["Topic"] == "Job" for e in frame["Events"])
        # Resume strictly after what we saw: no duplicates.
        resumed = api.event_stream(topics=["Job"],
                                   from_index=frame["Index"],
                                   heartbeat=0.5)
        job2 = mock.job()
        agent.rpc("Job.Register", {"Job": to_dict(job2)})
        frame2 = next(resumed)
        resumed.close()
        assert frame2["Index"] > frame["Index"]

    def test_topic_key_filter(self, event_agent):
        agent, api = event_agent
        j1, j2 = mock.job(), mock.job()
        stream = api.event_stream(topics=[f"Job:{j2.ID}"], from_index=0,
                                  heartbeat=0.5)
        agent.rpc("Job.Register", {"Job": to_dict(j1)})
        agent.rpc("Job.Register", {"Job": to_dict(j2)})
        frame = next(stream)
        stream.close()
        assert [e["Key"] for e in frame["Events"]] == [j2.ID]

    def test_heartbeats_prove_liveness(self, event_agent):
        agent, api = event_agent
        broker = agent.server.fsm.events
        tail = broker.stats()["Tail"]
        resp = urllib.request.urlopen(
            _stream_url(agent, f"index={tail}&heartbeat=0.2"), timeout=5)
        try:
            # Background scheduler traffic may interleave real frames;
            # a heartbeat must still arrive within a few cadences.
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                line = resp.readline().strip()
                if line and json.loads(line) == {}:
                    break
            else:
                pytest.fail("no heartbeat frame within 3s")
        finally:
            resp.close()

    def test_region_tag_and_header(self, event_agent):
        agent, api = event_agent
        resp = urllib.request.urlopen(
            _stream_url(agent, "index=0&heartbeat=0.2"), timeout=5)
        try:
            assert resp.headers["X-Nomad-Region"] == "global"
        finally:
            resp.close()

    @pytest.mark.parametrize("params,code", [
        ("topic=Bogus", 400),
        ("index=nope", 400),
        ("heartbeat=nope", 400),
        ("region=elsewhere", 400),
    ])
    def test_bad_params(self, event_agent, params, code):
        agent, _ = event_agent
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(_stream_url(agent, params), timeout=5)
        assert exc.value.code == code

    def test_method_not_allowed(self, event_agent):
        agent, _ = event_agent
        req = urllib.request.Request(_stream_url(agent), data=b"{}",
                                     method="PUT")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 405

    def test_gap_resume_is_416_with_floor(self, event_agent):
        """LAST in this class: resets the module agent's broker floor.
        A resume below the retained window is a typed, non-retryable
        416 carrying the floor to resubscribe from."""
        agent, api = event_agent
        broker = agent.server.fsm.events
        # Reset AT the current tail (the snapshot-install shape: state
        # jumped to the applied index) — a floor above the raft index
        # would discard every later publish as a replay.
        floor = broker.stats()["Tail"]
        assert floor > 1
        broker.reset(floor)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(_stream_url(agent, "index=1"),
                                   timeout=5)
        assert exc.value.code == 416
        body = json.loads(exc.value.read())
        assert body["Floor"] == floor
        with pytest.raises(EventGapAPIError) as api_exc:
            next(api.event_stream(from_index=1, reconnect_attempts=1))
        assert api_exc.value.floor == floor
        # The stream recovers at the new floor.
        stream = api.event_stream(from_index=floor, heartbeat=0.5)
        agent.rpc("Job.Register", {"Job": to_dict(mock.job())})
        assert next(stream)["Index"] > floor
        stream.close()


# ------------------------------------------------------------------ CLI

class TestEventsCLI:
    FRAMES = [
        {"Index": 7, "Events": [
            {"Topic": "Job", "Type": "JobRegistered", "Key": "web",
             "Index": 7, "Payload": {"ID": "web"}}]},
        {"Index": 9, "Dropped": 2, "Events": [
            {"Topic": "Alloc", "Type": "AllocPlaced", "Key": "a1",
             "Index": 9, "Payload": {"ID": "a1", "NodeID": "n1"}}]},
    ]

    def _run(self, argv, monkeypatch, capsys):
        from nomad_tpu.cli import commands

        def fake_stream(self, topics=None, from_index=0, fanout=False,
                        **kwargs):
            fake_stream.called_with = {"topics": topics,
                                       "from_index": from_index,
                                       "fanout": fanout}
            return iter(TestEventsCLI.FRAMES)

        monkeypatch.setattr(APIClient, "event_stream", fake_stream)
        rc = commands.main(argv)
        out, err = capsys.readouterr()
        return rc, out, err, fake_stream.called_with

    def test_events_json_output(self, monkeypatch, capsys):
        rc, out, err, called = self._run(
            ["events", "-json", "-topic", "Job", "-index", "5"],
            monkeypatch, capsys)
        assert rc == 0
        lines = [json.loads(line) for line in out.splitlines()]
        assert [e["Type"] for e in lines] == ["JobRegistered",
                                              "AllocPlaced"]
        assert called == {"topics": ["Job"], "from_index": 5,
                          "fanout": False}
        assert "2 frame(s) dropped" in err

    def test_events_table_output(self, monkeypatch, capsys):
        rc, out, _, called = self._run(["events", "-fanout"],
                                       monkeypatch, capsys)
        assert rc == 0
        assert called["fanout"] is True
        lines = out.splitlines()
        assert "JobRegistered" in lines[0] and "web" in lines[0]
        assert lines[1].lstrip().startswith("9")
