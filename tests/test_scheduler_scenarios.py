"""Deep scheduler scenario matrix (shaped after the reference's
scheduler/generic_sched_test.go scenarios not yet covered by
tests/test_scheduler.py: count-zero, alloc-fail metrics, mixed
feasible/infeasible groups, blocked-eval processing/reuse, node-limited
count increases, drain under an update strategy, batch rerun semantics)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import Constraint, Resources, TaskGroup, UpdateStrategy
from nomad_tpu.structs.structs import (
    SECOND,
    AllocClientStatusComplete,
    AllocClientStatusFailed,
    AllocClientStatusRunning,
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusBlocked,
    EvalStatusComplete,
    EvalStatusFailed,
    EvalStatusPending,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
    JobTypeBatch,
    NodeStatusDown,
)


def make_eval(job, trigger=EvalTriggerJobRegister,
              status=EvalStatusPending):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = status
    return ev


def placed_allocs(plan):
    return [a for allocs in plan.NodeAllocation.values() for a in allocs]


class TestRegisterEdges:
    def test_count_zero_is_noop_complete(self):
        """(reference: TestServiceSched_JobRegister_CountZero)"""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.job()
        job.TaskGroups[0].Count = 0
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert h.plans == []  # nothing to place -> no plan submitted
        assert h.evals[-1].Status == EvalStatusComplete

    def test_alloc_fail_fills_metrics(self):
        """Nodes exist but are too small: FailedTGAllocs carries the
        dimension-exhaustion diagnosis and a blocked eval is created
        (reference: TestServiceSched_JobRegister_AllocFail +
        CreateBlockedEval)."""
        h = Harness()
        for _ in range(2):
            node = mock.node()
            node.Resources.MemoryMB = 16  # too small for the mock task
            h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 1
        h.upsert("job", job)
        h.process("service", make_eval(job))

        assert h.plans == []
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        metric = final.FailedTGAllocs[job.TaskGroups[0].Name]
        assert metric.NodesEvaluated > 0
        assert any("memory" in dim for dim in metric.DimensionExhausted)
        # Blocked eval chained for when capacity frees.
        blocked = [e for e in h.creates
                   if e.Status == EvalStatusBlocked]
        assert len(blocked) == 1
        assert final.BlockedEval == blocked[0].ID
        # Class eligibility captured (all classes ineligible).
        assert blocked[0].ClassEligibility or blocked[0].EscapedComputedClass

    def test_feasible_and_infeasible_groups(self):
        """One group places, the other can't: plan carries the feasible
        placements AND the eval records the infeasible group's failure
        (reference: TestServiceSched_JobRegister_FeasibleAndInfeasibleTG)."""
        h = Harness()
        for _ in range(2):
            h.upsert("node", mock.node())
        job = mock.job()
        feasible = job.TaskGroups[0]
        feasible.Count = 2
        infeasible = feasible.copy()
        infeasible.Name = "hopeless"
        infeasible.Count = 1
        infeasible.Constraints = [Constraint(
            LTarget="${attr.kernel.name}", RTarget="plan9", Operand="=")]
        job.TaskGroups.append(infeasible)
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))

        assert len(h.plans) == 1
        placed = placed_allocs(h.plans[0])
        assert len(placed) == 2
        assert all(a.TaskGroup == feasible.Name for a in placed)
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        assert set(final.FailedTGAllocs) == {"hopeless"}


class TestBlockedEvalLifecycle:
    def _blocked_setup(self):
        """A job blocked on capacity: one tiny node, count 2 big asks."""
        h = Harness()
        node = mock.node()
        node.Resources.CPU = 700
        node.Resources.MemoryMB = 300
        h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 2
        task = job.TaskGroups[0].Tasks[0]
        task.Resources.CPU = 500
        task.Resources.MemoryMB = 256
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert len(h.creates) == 1  # blocked follow-up
        return h, job, h.creates[0]

    def test_blocked_eval_places_when_capacity_arrives(self):
        """Processing the blocked eval after a node joins places the
        remainder (reference: TestServiceSched_EvaluateBlockedEval +
        unblock flow)."""
        h, job, blocked = self._blocked_setup()
        h.upsert("node", mock.node())  # capacity arrives
        h.process("service", blocked)
        total = len(h.state.allocs_by_job(job.ID))
        assert total == 2
        assert h.evals[-1].Status == EvalStatusComplete
        # Fully placed: no re-block.
        assert len(h.creates) == 1
        assert h.reblocks == []

    def test_blocked_eval_still_short_reblocks(self):
        """A blocked eval that STILL can't fully place is re-blocked with
        refreshed class eligibility, not completed and not duplicated
        (reference: blocked-eval reuse, TestServiceSched_EvaluateBlockedEval
        remaining-capacity variant)."""
        h, job, blocked = self._blocked_setup()
        h.process("service", blocked)  # no new capacity
        assert h.reblocks, "expected the eval to re-block"
        assert h.reblocks[-1].ID == blocked.ID
        # Not completed, no second blocked eval created.
        assert len(h.creates) == 1

    def test_blocked_eval_finished_completes(self):
        """(reference: TestServiceSched_EvaluateBlockedEval_Finished)"""
        h, job, blocked = self._blocked_setup()
        big = mock.node()
        h.upsert("node", big)
        h.process("service", blocked)
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        assert not final.FailedTGAllocs


class TestModifyEdges:
    def test_modify_count_zero_stops_all(self):
        """(reference: TestServiceSched_JobModify_CountZero)"""
        h = Harness()
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            h.upsert("node", n)
        job = mock.job()
        job.TaskGroups[0].Count = 3
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert len(h.state.allocs_by_job(job.ID)) == 3

        update = job.copy()
        update.TaskGroups[0].Count = 0
        update.init_fields()
        h.upsert("job", update)
        h.process("service", make_eval(update))
        allocs = h.state.allocs_by_job(job.ID)
        stopped = [a for a in allocs
                   if a.DesiredStatus == AllocDesiredStatusStop]
        assert len(stopped) == 3
        assert h.evals[-1].Status == EvalStatusComplete

    def test_incr_count_beyond_capacity_partial_and_blocked(self):
        """Count increase that outgrows the cluster places what fits and
        blocks the rest (reference:
        TestServiceSched_JobModify_IncrCount_NodeLimit)."""
        h = Harness()
        node = mock.node()
        # Room for exactly two 1000MHz asks (mock nodes reserve 100MHz).
        node.Resources.CPU = 2200
        node.Resources.MemoryMB = 4096
        h.upsert("node", node)
        job = mock.job()
        job.TaskGroups[0].Count = 1
        task = job.TaskGroups[0].Tasks[0]
        task.Resources.CPU = 1000
        task.Resources.MemoryMB = 256
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert len(h.state.allocs_by_job(job.ID)) == 1

        update = job.copy()
        update.TaskGroups[0].Count = 5
        update.init_fields()
        h.upsert("job", update)
        h.process("service", make_eval(update))
        run_allocs = [a for a in h.state.allocs_by_job(job.ID)
                      if a.DesiredStatus == AllocDesiredStatusRun]
        assert 1 < len(run_allocs) < 5  # partial: capacity for 2 x 1000MHz
        final = h.evals[-1]
        assert final.FailedTGAllocs
        assert any(e.Status == EvalStatusBlocked for e in h.creates)


class TestDrainWithUpdateStrategy:
    def test_drain_migrates_respecting_stagger(self):
        """Draining with max_parallel=1 migrates one alloc per pass and
        chains a rolling-update follow-up eval with the stagger wait
        (reference: TestServiceSched_NodeDrain_UpdateStrategy)."""
        h = Harness()
        drain_node = mock.node()
        h.upsert("node", drain_node)
        for _ in range(2):
            h.upsert("node", mock.node())
        job = mock.job()
        job.TaskGroups[0].Count = 2
        job.Update = UpdateStrategy(Stagger=30 * SECOND, MaxParallel=1)
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert len(h.state.allocs_by_job(job.ID)) == 2

        # Drain every node that got an alloc... drain just one that did.
        victim_id = h.state.allocs_by_job(job.ID)[0].NodeID
        h.state.update_node_drain(h._next_index(), victim_id, True)
        on_victim = [a for a in h.state.allocs_by_job(job.ID)
                     if a.NodeID == victim_id]
        if len(on_victim) < 2:
            # Force both allocs onto the drained node's fate: drain all
            # nodes carrying allocs so two migrations are needed.
            for a in h.state.allocs_by_job(job.ID):
                h.state.update_node_drain(h._next_index(), a.NodeID, True)

        h.process("service", make_eval(job, EvalTriggerNodeUpdate))
        # Only max_parallel=1 migration this pass; a follow-up eval with
        # the stagger wait carries the rest.
        stops = [a for plan in h.plans for allocs in plan.NodeUpdate.values()
                 for a in allocs]
        assert len(stops) >= 1
        follow = [e for e in h.creates if e.Wait == 30 * SECOND]
        assert follow, "expected a stagger follow-up eval"


class TestBatchRerunSemantics:
    def _run_one(self, client_status):
        h = Harness()
        node = mock.node()
        h.upsert("node", node)
        job = mock.job()
        job.Type = JobTypeBatch
        job.TaskGroups[0].Count = 1
        job.init_fields()
        h.upsert("job", job)
        h.process("batch", make_eval(job))
        allocs = h.state.allocs_by_job(job.ID)
        assert len(allocs) == 1
        done = allocs[0].copy()
        done.ClientStatus = client_status
        h.upsert("allocs", [done])
        return h, job

    def test_failed_alloc_is_replaced(self):
        """(reference: TestBatchSched_Run_FailedAlloc)"""
        h, job = self._run_one(AllocClientStatusFailed)
        h.process("batch", make_eval(job))
        run = [a for a in h.state.allocs_by_job(job.ID)
               if a.DesiredStatus == AllocDesiredStatusRun
               and a.ClientStatus != AllocClientStatusFailed]
        assert len(run) == 1

    def test_successful_alloc_not_rerun(self):
        """(reference: TestBatchSched_ReRun_SuccessfullyFinishedAlloc)"""
        h, job = self._run_one(AllocClientStatusComplete)
        h.process("batch", make_eval(job))
        assert len(h.state.allocs_by_job(job.ID)) == 1  # nothing new

    def test_drained_alloc_is_migrated(self):
        """(reference: TestBatchSched_Run_DrainedAlloc)"""
        h = Harness()
        n1, n2 = mock.node(), mock.node()
        h.upsert("node", n1)
        h.upsert("node", n2)
        job = mock.job()
        job.Type = JobTypeBatch
        job.TaskGroups[0].Count = 1
        job.init_fields()
        h.upsert("job", job)
        h.process("batch", make_eval(job))
        alloc = h.state.allocs_by_job(job.ID)[0]
        h.state.update_node_drain(h._next_index(), alloc.NodeID, True)
        h.process("batch", make_eval(job, EvalTriggerNodeUpdate))
        allocs = h.state.allocs_by_job(job.ID)
        migrated = [a for a in allocs
                    if a.DesiredStatus == AllocDesiredStatusRun
                    and a.NodeID != alloc.NodeID]
        assert len(migrated) == 1
