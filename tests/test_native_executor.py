"""Native C++ executor (native/executor.cc): same spec/state/exit contract
as the Python supervisor, exercised through the real driver path."""

import json
import os
import signal
import subprocess

import pytest

from nomad_tpu.client.driver.base import native_executor_path

NATIVE = native_executor_path()

pytestmark = pytest.mark.skipif(
    not NATIVE, reason="native executor not built (make -C native)")


from helpers import wait_for  # noqa: E402

def launch(tmp_path, task="t1", **spec_extra):
    spec = {
        "task_name": task,
        "command": "/bin/sh",
        "args": ["-c", "echo out-line; echo err-line >&2; sleep 30"],
        "env": {"FOO": "bar"},
        "cwd": str(tmp_path),
        "log_dir": str(tmp_path / "logs"),
        "max_files": 2,
        "max_file_size_mb": 1,
    }
    spec.update(spec_extra)
    spec_path = tmp_path / f"{task}.executor_spec.json"
    spec_path.write_text(json.dumps(spec))
    proc = subprocess.Popen([NATIVE, str(spec_path)],
                            start_new_session=True)
    return proc, tmp_path / f"{task}.executor_state.json", \
        tmp_path / f"{task}.exit_status.json"


class TestNativeExecutor:
    def test_runs_logs_and_reports_exit(self, tmp_path):
        proc, state_path, exit_path = launch(
            tmp_path, args=["-c", "echo out-line; echo err-line >&2; exit 3"])
        assert wait_for(state_path.exists)
        state = json.loads(state_path.read_text())
        assert state["pid"] == state["pgid"]
        assert state["native"] is True
        assert wait_for(exit_path.exists)
        result = json.loads(exit_path.read_text())
        assert result["exit_code"] == 3 and result["signal"] == 0
        out = (tmp_path / "logs" / "t1.stdout.0").read_text()
        err = (tmp_path / "logs" / "t1.stderr.0").read_text()
        assert out == "out-line\n" and err == "err-line\n"
        proc.wait(timeout=5)

    def test_env_reaches_task(self, tmp_path):
        proc, state_path, exit_path = launch(
            tmp_path, args=["-c", "echo val=$FOO"])
        assert wait_for(exit_path.exists)
        assert "val=bar" in (tmp_path / "logs" / "t1.stdout.0").read_text()
        proc.wait(timeout=5)

    def test_sigterm_forwards_to_group(self, tmp_path):
        """Signal the SUPERVISOR: it must forward to the task's process
        group (the kill protocol the task runner uses)."""
        proc, state_path, exit_path = launch(tmp_path)
        assert wait_for(state_path.exists)
        state = json.loads(state_path.read_text())
        assert state["executor_pid"] == proc.pid
        os.kill(proc.pid, signal.SIGTERM)  # executor, not the task
        assert wait_for(exit_path.exists)
        result = json.loads(exit_path.read_text())
        assert result["signal"] == signal.SIGTERM
        proc.wait(timeout=5)

    def test_log_rotation(self, tmp_path):
        # ~3MB of output with 1MB files, keep 2.
        proc, state_path, exit_path = launch(
            tmp_path,
            args=["-c", "yes 0123456789012345678901234567890123456789 "
                        "| head -c 3000000"])
        assert wait_for(exit_path.exists, timeout=20)
        logs = sorted(p.name for p in (tmp_path / "logs").iterdir()
                      if p.name.startswith("t1.stdout"))
        assert len(logs) <= 2
        assert "t1.stdout.2" in logs  # rotated twice, oldest pruned
        proc.wait(timeout=5)

    def test_exec_failure_reports(self, tmp_path):
        proc, state_path, exit_path = launch(
            tmp_path, command="/does/not/exist", args=[])
        assert wait_for(exit_path.exists)
        assert json.loads(exit_path.read_text())["exit_code"] == 127
        proc.wait(timeout=5)


class TestNativeThroughDriver:
    def test_raw_exec_uses_native_and_reattaches(self, tmp_path, monkeypatch):
        """The full driver path on the native supervisor: start, read logs,
        reattach via handle id, kill via the handle."""
        from nomad_tpu import mock
        from nomad_tpu.client.allocdir import AllocDir
        from nomad_tpu.client.driver import new_driver
        from nomad_tpu.client.driver.base import DriverContext, ExecContext
        from nomad_tpu.client.env import TaskEnv

        class Cfg:
            state_dir = str(tmp_path / "state")
            alloc_dir = str(tmp_path / "alloc")
            options = {"driver.raw_exec.enable": "1"}

            def read_option(self, k, d=""):
                return self.options.get(k, d)

        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        task.Driver = "raw_exec"
        task.Config = {"command": "/bin/sleep", "args": ["30"]}
        adir = AllocDir(str(tmp_path / "alloc" / alloc.ID))
        adir.build([task.Name])
        env = TaskEnv(task=task, alloc=alloc)
        ctx = ExecContext(alloc_dir=adir, alloc_id=alloc.ID, task_env=env)
        driver = new_driver("raw_exec", DriverContext(task_name=task.Name,
                                                      config=Cfg()))
        handle = driver.start(ctx, task)
        try:
            # The state file records the native supervisor.
            import glob

            state_files = glob.glob(
                str(tmp_path / "**" / "*.executor_state.json"),
                recursive=True)
            assert state_files
            assert json.loads(open(state_files[0]).read()).get("native")

            # Reattach by handle id.
            handle2 = driver.open(ctx, handle.id())
            assert handle2.wait(timeout=0.3) is None  # still running

            # Stats flow through the pid tree.
            assert wait_for(lambda: handle.stats() is not None)
        finally:
            handle.kill(kill_timeout=2.0)
        result = handle.wait(timeout=10)
        assert result is not None and result.signal in (0, signal.SIGTERM)
