"""Adversarial raft fuzz: seeded random message drop / duplication /
delay (reorder) plus crash-restarts on an in-process cluster, asserting
the safety properties the scenario-shaped chaos suite cannot sweep
(reference frame: hashicorp/raft's fuzzy tests, vendored under
vendor/github.com/hashicorp/raft/ — TestRaft_*Partition* and the
fuzzy/ harness).

Invariants checked:
  - election safety: across the whole run, no term ever has two leaders
  - no committed-entry loss: every client-acknowledged command appears
    in every surviving FSM, exactly once, in submission order
  - log matching: after healing, all FSMs converge to identical
    (index, value) sequences
  - monotonic apply: each FSM instance sees strictly increasing indexes

The fault SCHEDULE derives from a seed (message-level decisions from one
RNG; the crash scheduler from another), so a failing seed reproduces the
same fault pattern even though thread interleaving stays nondeterministic.
CI runs ~3 seeds x ~4s (several hundred fault decisions each);
NOMAD_TPU_SOAK=1 extends to many seeds and longer runs.
"""

import os
import random
import threading
import time

import msgpack
import pytest

from nomad_tpu.raft import InMemLogStore, RaftNode
from nomad_tpu.raft.node import (
    ApplyTimeout,
    NotLeaderError,
    RaftConfig,
)
from nomad_tpu.raft.transport import (
    BoundTransport,
    InMemTransport,
    TransportError,
)

FAST = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.1,
                  election_timeout_max=0.2, apply_timeout=2.0,
                  snapshot_threshold=64, trailing_logs=32)


from test_raft import AppendFSM  # noqa: E402  (cross-test convention)


class RecordingFSM(AppendFSM):
    """AppendFSM plus a monotonic-apply check: indexes must strictly
    increase within one FSM instance (restarts create a new instance
    that resumes from the snapshot/log replay)."""

    def __init__(self):
        super().__init__()
        self.monotonic_ok = True

    def apply(self, index, etype, data):
        with self.lock:
            if self.applied and index <= self.applied[-1][0]:
                self.monotonic_ok = False
        return super().apply(index, etype, data)


class FuzzTransport(InMemTransport):
    """InMemTransport with seeded per-message faults: drops, duplicate
    delivery, and random delivery delay (concurrent senders + random
    delay = reordering). Faults apply on top of the partition/down
    controls of the base class."""

    def __init__(self, seed: int, p_drop=0.08, p_dup=0.05, max_delay=0.03):
        super().__init__()
        self._rng = random.Random(seed)
        self._frng_lock = threading.Lock()
        self.p_drop = p_drop
        self.p_dup = p_dup
        self.max_delay = max_delay
        self.faults = {"drop": 0, "dup": 0, "delay": 0, "sent": 0}

    def _decide(self):
        with self._frng_lock:
            return (self._rng.random(), self._rng.random(),
                    self._rng.random() * self.max_delay
                    if self._rng.random() < 0.5 else 0.0)

    def send(self, target, method, payload, source=None):
        r_drop, r_dup, delay = self._decide()
        self.faults["sent"] += 1
        if r_drop < self.p_drop:
            self.faults["drop"] += 1
            raise TransportError(f"fuzz: dropped {method} to {target}")
        if delay:
            self.faults["delay"] += 1
            time.sleep(delay)
        resp = super().send(target, method, payload, source=source)
        if r_dup < self.p_dup:
            # Duplicate delivery: the peer processes the message twice
            # (raft must be idempotent to redelivery); the caller sees
            # the second response, as a retransmit's caller would.
            self.faults["dup"] += 1
            try:
                resp = super().send(target, method, payload, source=source)
            except TransportError:
                pass
        return resp


class FuzzCluster:
    def __init__(self, n, seed):
        self.transport = FuzzTransport(seed)
        self.ids = [f"f{i}" for i in range(n)]
        self.stores = {nid: InMemLogStore() for nid in self.ids}
        self.fsms = {}
        self.retired_fsms = []
        self.nodes = {}
        for nid in self.ids:
            self._spawn(nid)
        # {term: leader_id} observed across the whole run.
        self.leaders_by_term = {}
        self.violations = []

    def _spawn(self, nid):
        fsm = RecordingFSM()
        node = RaftNode(
            node_id=nid, peers=list(self.ids),
            log_store=self.stores[nid],
            transport=BoundTransport(self.transport, nid),
            apply_fn=fsm.apply, snapshot_fn=fsm.snapshot,
            restore_fn=fsm.restore, config=FAST)
        self.fsms[nid] = fsm
        self.nodes[nid] = node
        node.start()

    def crash(self, nid):
        node = self.nodes.pop(nid, None)
        if node is None:
            return
        node.shutdown()
        self.retired_fsms.append(self.fsms.pop(nid))

    def restart(self, nid):
        if nid not in self.nodes:
            self._spawn(nid)

    def sample_leaders(self):
        for nid, node in list(self.nodes.items()):
            try:
                # stats() reads state+term under ONE lock: separate
                # role/term reads could pair a stale leadership with a
                # just-bumped term and report a spurious violation.
                st = node.stats()
                if st["state"] != "leader":
                    continue
                term = st["term"]
                seen = self.leaders_by_term.get(term)
                if seen is None:
                    self.leaders_by_term[term] = nid
                elif seen != nid:
                    self.violations.append(
                        f"term {term}: leaders {seen} and {nid}")
            except Exception:
                pass

    def leader(self):
        live = [n for n in list(self.nodes.values())
                if n.is_leader() and n.role == "leader"]
        return live[0] if len(live) == 1 else None

    def shutdown(self):
        for node in list(self.nodes.values()):
            node.shutdown()


def _run_fuzz(seed, duration, n=3, crash_period=(0.4, 0.9)):
    cluster = FuzzCluster(n, seed)
    crng = random.Random(seed ^ 0xC0FFEE)
    stop = threading.Event()
    acked = []
    seq = iter(range(10 ** 9))

    def submitter():
        while not stop.is_set():
            value = f"v{next(seq)}"
            try:
                leader = cluster.leader()
                if leader is None:
                    time.sleep(0.02)
                    continue
                leader.apply_command(
                    msgpack.packb(value, use_bin_type=True), timeout=2.0)
                acked.append(value)
            except (NotLeaderError, ApplyTimeout, TransportError,
                    RuntimeError):
                pass  # unknown outcome: value may or may not commit
            time.sleep(0.01)

    def sampler():
        while not stop.is_set():
            cluster.sample_leaders()
            time.sleep(0.01)

    threads = [threading.Thread(target=submitter, daemon=True),
               threading.Thread(target=sampler, daemon=True)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            time.sleep(crng.uniform(*crash_period))
            if crng.random() < 0.6 and len(cluster.nodes) == len(
                    cluster.ids):
                victim = crng.choice(cluster.ids)
                cluster.crash(victim)
                time.sleep(crng.uniform(0.2, 0.5))
                cluster.restart(victim)
        stop.set()
        for t in threads:
            t.join(timeout=5)

        # Heal: lift all faults, restart anything down, require
        # convergence.
        cluster.transport.p_drop = 0.0
        cluster.transport.p_dup = 0.0
        cluster.transport.max_delay = 0.0
        for nid in cluster.ids:
            cluster.restart(nid)
        final = f"final-{seed}"
        deadline = time.monotonic() + 20
        committed_final = False
        while time.monotonic() < deadline and not committed_final:
            leader = cluster.leader()
            if leader is not None:
                try:
                    leader.apply_command(
                        msgpack.packb(final, use_bin_type=True),
                        timeout=2.0)
                    committed_final = True
                except (NotLeaderError, ApplyTimeout, TransportError,
                        RuntimeError):
                    pass
            time.sleep(0.05)
        assert committed_final, "cluster never converged after healing"

        # Wait for every FSM to observe the final barrier entry.
        def all_caught_up():
            return all(any(v == final for _, v in f.applied)
                       for f in cluster.fsms.values())
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not all_caught_up():
            time.sleep(0.05)

        # ---- invariants
        assert not cluster.violations, cluster.violations  # election safety
        sequences = {nid: list(f.applied)
                     for nid, f in cluster.fsms.items()}
        # Log matching: identical committed sequences everywhere.
        ref = None
        for nid, seq_ in sequences.items():
            assert seq_, f"{nid} applied nothing"
            if ref is None:
                ref = seq_
            else:
                assert seq_ == ref, (
                    f"{nid} diverged: {seq_[-5:]} vs {ref[-5:]}")
        # No committed-entry loss or reordering: acked values appear in
        # submission order, exactly once each.
        values = [v for _, v in ref]
        assert len(values) == len(set(values)), "duplicate applied entry"
        pos = {v: i for i, v in enumerate(values)}
        missing = [v for v in acked if v not in pos]
        assert not missing, f"acked entries lost: {missing[:5]}"
        order = [pos[v] for v in acked]
        assert order == sorted(order), "acked entries reordered"
        # Monotonic apply within every FSM incarnation.
        for f in list(cluster.fsms.values()) + cluster.retired_fsms:
            assert f.monotonic_ok, "non-monotonic apply index"
        stats = dict(cluster.transport.faults)
        stats["acked"] = len(acked)
        return stats
    finally:
        stop.set()
        cluster.shutdown()


SOAK = bool(os.environ.get("NOMAD_TPU_SOAK"))


class TestRaftFuzz:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_seeded_fuzz(self, seed):
        stats = _run_fuzz(seed, duration=4.0)
        # The run must actually have exercised faults and commits.
        assert stats["drop"] > 20, stats
        assert stats["dup"] > 5, stats
        assert stats["acked"] > 10, stats

    @pytest.mark.skipif(not SOAK,
                        reason="set NOMAD_TPU_SOAK=1 for the extended soak")
    @pytest.mark.parametrize("seed", list(range(100, 112)))
    def test_soak_fuzz(self, seed):
        stats = _run_fuzz(seed, duration=15.0, n=5,
                          crash_period=(0.3, 0.7))
        assert stats["acked"] > 30, stats
