"""State store parity grid (reference: nomad/state/state_store_test.go —
the query/index/launch cases beyond test_state_store.py's core CRUD,
snapshot, and compaction coverage)."""

from nomad_tpu import mock
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import PeriodicConfig, PeriodicLaunch
from nomad_tpu.structs.structs import (
    AllocClientStatusComplete,
    AllocDesiredStatusEvict,
    JobTypeBatch,
    PeriodicSpecTest,
)


def _store():
    return StateStore()


class TestPrefixQueries:
    def test_jobs_by_id_prefix(self):
        """(reference: TestStateStore_JobsByIDPrefix): shared prefixes
        return every match; extending the prefix narrows to one; a
        non-matching prefix returns none."""
        state = _store()
        job = mock.job()
        job.ID = "redis"
        state.upsert_job(1000, job)
        assert len(state.jobs_by_id_prefix("re")) == 1
        assert len(state.jobs_by_id_prefix("redis")) == 1

        job2 = mock.job()
        job2.ID = "riak"
        state.upsert_job(1001, job2)
        assert len(state.jobs_by_id_prefix("r")) == 2
        assert len(state.jobs_by_id_prefix("ri")) == 1
        assert state.jobs_by_id_prefix("nomatch") == []


class TestJobsByGC:
    def test_batch_jobs_are_gc_eligible(self):
        """(reference: TestStateStore_JobsByGC): service and periodic
        jobs are not GC-able; batch jobs are."""
        state = _store()
        service_jobs = []
        periodic_batch = []
        for i in range(6):
            if i % 2 == 0:
                job = mock.job()
                service_jobs.append(job)
            else:
                job = mock.job()
                job.Type = JobTypeBatch
                job.Periodic = PeriodicConfig(
                    Enabled=True, SpecType=PeriodicSpecTest, Spec="1")
                periodic_batch.append(job)
            state.upsert_job(1000 + i, job)
        gc = []
        for i in range(4):
            job = mock.job()
            job.Type = JobTypeBatch
            gc.append(job)
            state.upsert_job(2000 + i, job)
        out_gc = {j.ID for j in state.jobs_by_gc(True)}
        out_non = {j.ID for j in state.jobs_by_gc(False)}
        for j in gc:
            assert j.ID in out_gc
        for j in service_jobs:
            assert j.ID in out_non
        # Our store keys GC-eligibility on Type==batch alone; periodic
        # batch PARENTS therefore show as eligible here, and the core
        # GC's status check is what protects live parents (a documented
        # deviation from jobIsGCable, which also excludes periodic).
        for j in periodic_batch:
            assert j.ID in out_gc
        assert out_gc | out_non == {j.ID for j in service_jobs} \
            | {j.ID for j in periodic_batch} | {j.ID for j in gc}


class TestIndexes:
    def test_table_and_latest_index_tracking(self):
        """(reference: TestStateStore_Indexes + LatestIndex): each table
        remembers its own last write; latest_index is the max."""
        state = _store()
        state.upsert_node(1000, mock.node())
        assert state.get_index("nodes") == 1000
        state.upsert_job(1001, mock.job())
        assert state.get_index("jobs") == 1001
        assert state.get_index("nodes") == 1000
        assert state.latest_index() == 1001
        # Unknown table reads as 0.
        assert state.get_index("nope") == 0


class TestPeriodicLaunches:
    def test_upsert_get_update_delete(self):
        """(reference: TestStateStore_UpsertPeriodicLaunch +
        UpdateUpsert + Delete + PeriodicLaunches)"""
        state = _store()
        job = mock.job()
        launch = PeriodicLaunch(ID=job.ID, Launch=1_700_000_000.0)
        state.upsert_periodic_launch(1000, launch)
        out = state.periodic_launch_by_id(job.ID)
        assert out is not None
        assert out.Launch == launch.Launch
        assert state.get_index("periodic_launch") == 1000

        # Update advances the launch time in place.
        later = PeriodicLaunch(ID=job.ID, Launch=1_700_000_600.0)
        state.upsert_periodic_launch(1001, later)
        assert state.periodic_launch_by_id(job.ID).Launch == later.Launch
        assert len(state.periodic_launches()) == 1

        state.delete_periodic_launch(1002, job.ID)
        assert state.periodic_launch_by_id(job.ID) is None
        assert state.periodic_launches() == []


class TestAllocQueries:
    def test_allocs_by_node_terminal_split(self):
        """(reference: TestStateStore_AllocsByNodeTerminal; overlaps
        test_state_store.py's test_terminal_filter deliberately — this
        is the case-for-case reference port at its shape: four allocs,
        evict-terminal rather than stop-terminal)."""
        state = _store()
        node = mock.node()
        state.upsert_node(999, node)
        live, dead = [], []
        for i in range(4):
            alloc = mock.alloc()
            alloc.Job = None
            alloc.NodeID = node.ID
            if i % 2 == 0:
                alloc.DesiredStatus = AllocDesiredStatusEvict
                dead.append(alloc)
            else:
                live.append(alloc)
        state.upsert_allocs(1000, live + dead)
        out_live = state.allocs_by_node_terminal(node.ID, False)
        out_dead = state.allocs_by_node_terminal(node.ID, True)
        assert {a.ID for a in out_live} == {a.ID for a in live}
        assert {a.ID for a in out_dead} == {a.ID for a in dead}

    def test_evict_transition(self):
        """(reference: TestStateStore_EvictAlloc_Alloc): re-upserting an
        alloc with DesiredStatus=evict makes it terminal."""
        state = _store()
        node = mock.node()
        state.upsert_node(999, node)
        alloc = mock.alloc()
        alloc.Job = None
        alloc.NodeID = node.ID
        state.upsert_allocs(1000, [alloc])
        evict = alloc.copy()
        evict.DesiredStatus = AllocDesiredStatusEvict
        state.upsert_allocs(1001, [evict])
        out = state.alloc_by_id(alloc.ID)
        assert out.DesiredStatus == AllocDesiredStatusEvict
        assert out.terminal_status()
        assert state.allocs_by_node_terminal(node.ID, False) == []

    def test_client_update_preserves_server_fields(self):
        """(reference: TestStateStore_UpdateAllocsFromClient): a client
        status report updates ClientStatus/TaskStates but never the
        server-owned desired state, and bumps ModifyIndex only."""
        state = _store()
        node = mock.node()
        state.upsert_node(999, node)
        alloc = mock.alloc()
        alloc.Job = None
        alloc.NodeID = node.ID
        state.upsert_allocs(1000, [alloc])
        report = alloc.copy()
        report.ClientStatus = AllocClientStatusComplete
        report.DesiredStatus = "hacked"  # must NOT take effect
        state.update_alloc_from_client(1001, report)
        out = state.alloc_by_id(alloc.ID)
        assert out.ClientStatus == AllocClientStatusComplete
        assert out.DesiredStatus == alloc.DesiredStatus
        assert out.CreateIndex == 1000
        assert out.ModifyIndex == 1001
