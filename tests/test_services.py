"""Service discovery & health checking (nomad_tpu/services/).

The registry replaces the reference's external-Consul delegation
(command/agent/consul/syncer.go): replicated registrations, node-local
check runners, check-driven restarts, server self-registration.
"""

import http.server
import json
import threading
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.services import ServiceManager, run_check
from nomad_tpu.structs import (
    CheckState,
    Node,
    Service,
    ServiceCheck,
    ServiceRegistration,
    from_dict,
    to_dict,
)
from nomad_tpu.structs.structs import (
    SECOND,
    CheckStatusCritical,
    CheckStatusPassing,
)


from helpers import wait_for  # noqa: E402

def reg(id_="r1", name="web", node="n1", alloc="a1", **kw):
    return ServiceRegistration(ID=id_, ServiceName=name, NodeID=node,
                               AllocID=alloc, **kw)


# --------------------------------------------------------------- state store
class TestRegistryState:
    def test_upsert_query_delete(self):
        fsm = FSM()
        fsm.apply(10, MessageType.ServiceSync,
                  {"Upserts": [reg(), reg("r2", name="db", node="n2")]})
        assert {s.ServiceName for s in fsm.state.services()} == {"web", "db"}
        assert fsm.state.services_by_name("web")[0].ID == "r1"
        assert fsm.state.services_by_node("n2")[0].ID == "r2"
        assert fsm.state.service_by_id("r1").CreateIndex == 10

        fsm.apply(11, MessageType.ServiceSync, {"Deletes": ["r1"]})
        assert fsm.state.services_by_name("web") == []
        # idempotent double-delete
        fsm.apply(12, MessageType.ServiceSync, {"Deletes": ["r1"]})

    def test_node_down_marks_services_critical(self):
        """A down node's instances must stop being served as healthy (the
        reference relies on Consul's serfHealth for this)."""
        from nomad_tpu.structs.structs import NodeStatusDown

        fsm = FSM()
        node = mock.node()
        fsm.apply(5, MessageType.NodeRegister, {"Node": node})
        fsm.apply(6, MessageType.ServiceSync, {"Upserts": [reg(
            node=node.ID, Status=CheckStatusPassing,
            Checks=[CheckState(Name="c", Status=CheckStatusPassing)])]})
        fsm.apply(7, MessageType.NodeUpdateStatus,
                  {"NodeID": node.ID, "Status": NodeStatusDown})
        got = fsm.state.services_by_name("web")[0]
        assert got.Status == CheckStatusCritical
        assert got.Checks[0].Output == "node down"
        assert got.ModifyIndex == 7  # blocking watchers see the transition

    def test_node_delete_cascades_services(self):
        fsm = FSM()
        node = mock.node()
        fsm.apply(5, MessageType.NodeRegister, {"Node": node})
        fsm.apply(6, MessageType.ServiceSync,
                  {"Upserts": [reg(node=node.ID)]})
        fsm.apply(7, MessageType.NodeDeregister, {"NodeID": node.ID})
        assert fsm.state.services() == []

    def test_snapshot_restore_roundtrip(self):
        fsm = FSM()
        fsm.apply(10, MessageType.ServiceSync,
                  {"Upserts": [reg(Status=CheckStatusPassing,
                                   Checks=[CheckState(Name="c1",
                                                      Status="passing")])]})
        blob = fsm.snapshot()
        fsm2 = FSM()
        fsm2.restore(json.loads(json.dumps(blob)))
        got = fsm2.state.services_by_name("web")
        assert len(got) == 1 and got[0].Checks[0].Name == "c1"
        assert fsm2.state.get_index("services") == 10

    def test_watch_fires_on_service_change(self):
        from nomad_tpu.state.watch import Item

        fsm = FSM()
        ev = threading.Event()
        fsm.state.watch([Item(service_name="web")], ev)
        fsm.apply(3, MessageType.ServiceSync, {"Upserts": [reg()]})
        assert ev.is_set()

    def test_identical_upsert_is_a_noop(self):
        """Anti-entropy full syncs re-push every registration ~30s; an
        unchanged payload must not bump indexes or wake blocking watchers."""
        from nomad_tpu.state.watch import Item

        fsm = FSM()
        first = reg(Status=CheckStatusPassing,
                    Checks=[CheckState(Name="c", Status=CheckStatusPassing,
                                       Timestamp=1.0)])
        fsm.apply(10, MessageType.ServiceSync, {"Upserts": [first]})
        ev = threading.Event()
        fsm.state.watch([Item(service_name="web")], ev)

        # Same content, fresh check timestamp (every run re-stamps it).
        dup = reg(Status=CheckStatusPassing,
                  Checks=[CheckState(Name="c", Status=CheckStatusPassing,
                                     Timestamp=99.0)])
        fsm.apply(11, MessageType.ServiceSync, {"Upserts": [dup]})
        assert not ev.is_set()
        assert fsm.state.get_index("services") == 10
        assert fsm.state.service_by_id("r1").ModifyIndex == 10

        # A REAL change (check went critical) still writes + notifies.
        changed = reg(Status=CheckStatusCritical,
                      Checks=[CheckState(Name="c",
                                         Status=CheckStatusCritical)])
        fsm.apply(12, MessageType.ServiceSync, {"Upserts": [changed]})
        assert ev.is_set()
        assert fsm.state.service_by_id("r1").ModifyIndex == 12


class TestRegistryWire:
    def test_registration_codec_roundtrip(self):
        """Nested CheckState survives both codec paths (dict + msgpack)."""
        from nomad_tpu.structs import decode, encode

        r = reg(Status=CheckStatusPassing,
                Checks=[CheckState(Name="c", Type="tcp",
                                   Status=CheckStatusPassing,
                                   Output="ok", Timestamp=1.5)])
        assert from_dict(ServiceRegistration, to_dict(r)) == r
        assert decode(ServiceRegistration, encode(r)) == r

    def test_sync_and_query_over_real_rpc(self):
        """Service.Sync / Service.GetService over actual TCP framing — the
        dev-agent path is in-process, so this is where msgpack-wire
        serialization of registrations is exercised."""
        from nomad_tpu.rpc.cluster import ClusterServer
        from nomad_tpu.rpc.pool import ConnPool
        from nomad_tpu.server import ServerConfig

        cs = ClusterServer(ServerConfig(num_schedulers=0,
                                        bootstrap_expect=1))
        # Static single-node peer set: electable immediately, no gossip.
        cs.connect([cs.addr])
        cs.start()
        pool = ConnPool()
        try:
            wait_for(lambda: cs.server.is_leader())
            r = reg(Checks=[CheckState(Name="c", Type="http",
                                       Status=CheckStatusCritical,
                                       Output="boom")])
            resp = pool.call(cs.addr, "Service.Sync",
                             {"Upserts": [to_dict(r)], "Deletes": []})
            assert resp["Index"] > 0
            got = pool.call(cs.addr, "Service.GetService",
                            {"ServiceName": "web"})
            assert len(got["Services"]) == 1
            wire_reg = from_dict(ServiceRegistration, got["Services"][0])
            assert wire_reg.Checks[0].Output == "boom"
            assert wire_reg.Checks[0].Status == CheckStatusCritical

            pool.call(cs.addr, "Service.Sync",
                      {"Upserts": [], "Deletes": [r.ID]})
            got = pool.call(cs.addr, "Service.GetService",
                            {"ServiceName": "web"})
            assert got["Services"] == []
        finally:
            pool.close()
            cs.shutdown()


# -------------------------------------------------------------- check runners
class _Handler(http.server.BaseHTTPRequestHandler):
    status_code = 200

    def do_GET(self):
        self.send_response(type(self).status_code)
        self.end_headers()
        self.wfile.write(b"ok")

    def log_message(self, *args):
        pass


@pytest.fixture()
def http_target():
    srv = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


class TestCheckRunners:
    def test_http_check_statuses(self, http_target):
        port = http_target.server_address[1]
        check = ServiceCheck(Name="h", Type="http", Path="/health",
                             Interval=10 * SECOND, Timeout=2 * SECOND)
        status, _ = run_check(check, "127.0.0.1", port)
        assert status == CheckStatusPassing
        _Handler.status_code = 500
        try:
            status, out = run_check(check, "127.0.0.1", port)
            assert status == CheckStatusCritical and "500" in out
        finally:
            _Handler.status_code = 200

    def test_tcp_check(self, http_target):
        port = http_target.server_address[1]
        check = ServiceCheck(Name="t", Type="tcp", Interval=10 * SECOND,
                             Timeout=2 * SECOND)
        assert run_check(check, "127.0.0.1", port)[0] == CheckStatusPassing
        assert run_check(check, "127.0.0.1", 1)[0] == CheckStatusCritical

    def test_script_check_exit_codes(self, tmp_path):
        check = ServiceCheck(Name="s", Type="script", Command="/bin/sh",
                             Args=["-c", "echo fine"], Interval=10 * SECOND,
                             Timeout=5 * SECOND)
        status, out = run_check(check, "", 0, cwd=str(tmp_path))
        assert status == CheckStatusPassing and "fine" in out
        check.Args = ["-c", "exit 2"]
        assert run_check(check, "", 0)[0] == CheckStatusCritical


# ------------------------------------------------------------ service manager
def _node():
    node = mock.node()
    node.Attributes["unique.network.ip-address"] = "127.0.0.1"
    return node


class TestServiceManager:
    def test_register_resolves_ports_and_syncs(self):
        synced = []
        mgr = ServiceManager(_node(), lambda up, de: synced.append((up, de)))
        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        task.Services = [Service(Name="web", PortLabel="http",
                                 Tags=["frontend"])]
        task.Services[0].init_fields(alloc.JobID, "tg", task.Name)
        from nomad_tpu.structs import NetworkResource, Port, Resources

        task.Resources = Resources(Networks=[NetworkResource(
            IP="10.0.0.5", DynamicPorts=[Port(Label="http", Value=22000)])])
        mgr.register_task(alloc, task)
        assert wait_for(lambda: synced)
        up, de = synced[0]
        assert up[0].ServiceName == "web" and up[0].Port == 22000
        assert up[0].Address == "10.0.0.5"
        assert up[0].Status == CheckStatusPassing  # no checks -> passing

        mgr.deregister_task(alloc.ID, task.Name)
        assert wait_for(lambda: any(de for _, de in synced))
        mgr.shutdown()

    def test_reregistration_reconciles(self):
        """An in-place update re-registers with the new definition and
        deregisters services dropped from the task (reference: the syncer's
        desired-vs-registered diff)."""
        synced = []
        mgr = ServiceManager(_node(), lambda up, de: synced.append((up, de)))
        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        task.Services = [Service(Name="web", PortLabel=""),
                         Service(Name="old", PortLabel="")]
        mgr.register_task(alloc, task)

        updated = task.copy()
        updated.Services = [Service(Name="web", PortLabel="",
                                    Tags=["v2"])]
        mgr.register_task(alloc, updated)

        def flat():
            ups = {r.ID: r for up, _ in synced for r in up}
            des = {d for _, de in synced for d in de}
            return ups, des
        assert wait_for(lambda: any("old" in d for d in flat()[1]))
        ups, des = flat()
        web_id = f"_nomad-task-{alloc.ID}-{task.Name}-web"
        assert ups[web_id].Tags == ["v2"] or wait_for(
            lambda: flat()[0][web_id].Tags == ["v2"])
        mgr.shutdown()

    def test_failed_flush_retry_skips_reregistered_deletes(self):
        """A delete that failed to sync must NOT be retried once the same
        ID has been re-registered — the upsert+delete pair would land in
        one batch and the FSM (upserts, then deletes) would deregister the
        live service until the next anti-entropy full sync."""
        fail = [True]
        synced = []

        def sync_fn(up, de):
            if fail[0]:
                raise ConnectionError("leader unreachable")
            synced.append((up, de))

        mgr = ServiceManager(_node(), sync_fn)
        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        task.Services = [Service(Name="web", PortLabel="")]
        rid = f"_nomad-task-{alloc.ID}-{task.Name}-web"

        mgr.register_task(alloc, task)
        mgr._flush()                      # upsert lost (sync down)
        mgr.deregister_task(alloc.ID, task.Name)
        mgr._flush()                      # delete lost too, queued for retry
        mgr.register_task(alloc, task)    # service comes back
        fail[0] = False
        mgr._flush()

        ups = {r.ID for up, _ in synced for r in up}
        des = {d for _, de in synced for d in de}
        assert rid in ups
        assert rid not in des             # stale delete was dropped
        mgr.shutdown()

    def test_check_failure_triggers_restart(self, http_target):
        port = http_target.server_address[1]
        restarts = []
        mgr = ServiceManager(_node(), lambda up, de: None,
                             restart_fn=lambda a, t, r: restarts.append(r),
                             critical_threshold=2)
        # Fast checks for the test: 1s floor in _schedule.
        alloc = mock.alloc()
        task = alloc.Job.TaskGroups[0].Tasks[0]
        svc = Service(Name="web", PortLabel="http", Checks=[
            ServiceCheck(Name="alive", Type="http", Path="/",
                         Interval=10 * SECOND, Timeout=2 * SECOND)])
        task.Services = [svc]
        from nomad_tpu.structs import NetworkResource, Port, Resources

        task.Resources = Resources(Networks=[NetworkResource(
            IP="127.0.0.1", DynamicPorts=[Port(Label="http", Value=port)])])
        # shrink the interval floor by scheduling directly
        import nomad_tpu.services.manager as mgr_mod

        orig = mgr_mod.ns_to_seconds
        mgr_mod.ns_to_seconds = lambda ns: 0.0  # -> 1.0s floor... still slow
        try:
            mgr.register_task(alloc, task)
            # wait for a first passing run
            def statuses():
                with mgr._lock:
                    return [c.state.Status for i in mgr._instances.values()
                            for c in i.checks]
            assert wait_for(lambda: CheckStatusPassing in statuses(),
                            timeout=15)
            http_target.shutdown()  # service goes dark
            assert wait_for(lambda: restarts, timeout=15)
            assert "critical" in restarts[0]
        finally:
            mgr_mod.ns_to_seconds = orig
            mgr.shutdown()


# --------------------------------------------------- end-to-end via dev agent
class TestServiceE2E:
    def test_dev_agent_service_lifecycle(self, tmp_path):
        """Task with a service + http check registers, goes critical when its
        port goes dark, and the task restarts per policy."""
        from nomad_tpu.agent import Agent, AgentConfig
        from nomad_tpu.api import Client as ApiClient

        conf = AgentConfig.dev()
        conf.http_port = 0  # ephemeral
        conf.data_dir = str(tmp_path)
        agent = Agent(conf)
        agent.start()
        try:
            api = ApiClient(f"http://127.0.0.1:{agent.http.port}")
            # The task itself serves nothing: check goes critical after start.
            job = mock.job()
            job.ID = "svc-job"
            job.Name = "svc-job"
            tg = job.TaskGroups[0]
            tg.Count = 1
            tg.RestartPolicy.Attempts = 1
            tg.RestartPolicy.Delay = 1 * SECOND
            task = tg.Tasks[0]
            task.Driver = "raw_exec"
            task.Config = {"command": "/bin/sleep", "args": ["300"]}
            task.Services = [Service(Name="sleepy-http", PortLabel="http",
                                     Checks=[ServiceCheck(
                                         Name="ping", Type="tcp",
                                         Interval=10 * SECOND,
                                         Timeout=1 * SECOND)])]
            task.Services[0].init_fields(job.ID, tg.Name, task.Name)
            from nomad_tpu.structs import NetworkResource, Port

            task.Resources.Networks = [NetworkResource(
                MBits=1, DynamicPorts=[Port(Label="http")])]
            job.init_fields()
            api.jobs.register(job)

            # Service shows up in the registry via /v1/service/<name>
            def registered():
                regs, _ = api.services.get("sleepy-http")
                return regs
            assert wait_for(lambda: registered(), timeout=20)
            regs = registered()
            assert regs[0]["TaskName"] == task.Name
            assert regs[0]["Port"] > 0

            # Nothing listens on the assigned port: the tcp check goes
            # critical and the status propagates to the registry.
            assert wait_for(
                lambda: (registered() or [{}])[0].get("Status")
                == CheckStatusCritical, timeout=30)

            # Server self-registration: nomad-server instances queryable.
            srv_regs, _ = api.services.get("nomad-server")
            assert any("http" in r["Tags"] for r in srv_regs)

            services, _ = api.services.list()
            names = {s["ServiceName"] for s in services}
            assert {"sleepy-http", "nomad-server"} <= names

            # Client server-list bootstrap from the registry: an rpc-tagged
            # server registration is discoverable via any agent's HTTP API.
            from nomad_tpu.client.rpc import discover_servers
            from nomad_tpu.services import build_server_service_regs

            agent.server.service_sync(
                build_server_service_regs("srv2", rpc_addr="10.1.2.3:4647"),
                [])
            addrs = discover_servers(f"127.0.0.1:{agent.http.port}")
            assert "10.1.2.3:4647" in addrs
        finally:
            agent.shutdown()

    def test_graceful_shutdown_deregisters_server(self, tmp_path):
        from nomad_tpu.agent import Agent, AgentConfig

        conf = AgentConfig.dev()
        conf.http_port = 0
        conf.data_dir = str(tmp_path)
        agent = Agent(conf)
        agent.start()
        server = agent.server
        assert wait_for(
            lambda: server.state.services_by_name("nomad-server"))
        agent.shutdown()
        assert server.state.services_by_name("nomad-server") == []
