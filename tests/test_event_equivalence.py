"""Fixed-seed event-stream oracle: the stream IS the state.

Folding every published event into shadow state must reproduce the
StateStore the same applies built — on the object commit path AND the
columnar sweep path (where one AllocationBatch event's row/count
descriptor must expand to exactly the committed placements). The gate
is bidirectional: an `events.publish` drop keeps the FSM perfectly
healthy (NEVER FSM-visible) but must surface here as a fold-vs-store
mismatch — subscriber-visible loss the ring-integrity check cannot see,
because coverage still advances.

Events disabled (`event_buffer_size=0`) must be free: the same storm
produces bit-identical placements and the FSM carries no broker at all
(the disarmed cost is one attribute check on the apply path).
"""

import time
import types

import msgpack
import pytest

from nomad_tpu import mock
from nomad_tpu.events import EventBroker, expand_batch
from nomad_tpu.resilience import failpoints
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.plan_apply import _encode_result
from nomad_tpu.structs import PlanResult, to_dict
from nomad_tpu.structs.structs import EvalStatusComplete

from helpers import wait_for  # noqa: E402
from test_columnar_store_equivalence import (  # noqa: E402
    make_node,
    service_window,
    svc_job,
    sweep_plan,
    sys_job,
)

APPLY_INDEX = 100


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


# ------------------------------------------------------------- plumbing

def fsm_with_broker(size=4096):
    fsm = FSM()
    fsm.events = EventBroker(size=size)
    return fsm


def columnar_entry(plan):
    """The sweep's real wire shape (msgpack round-trip included)."""
    result = PlanResult(NodeUpdate=dict(plan.NodeUpdate),
                        NodeAllocation=dict(plan.NodeAllocation))
    result._sweep = plan._sweep
    element, is_sweep = _encode_result(plan, result)
    assert is_sweep
    blob = msgpack.packb(
        (int(MessageType.ApplySweepBatch), to_dict({"Batch": [element]})),
        use_bin_type=True)
    return msgpack.unpackb(blob, raw=False)


def object_entry(plan):
    blob = msgpack.packb(
        (int(MessageType.AllocUpdate),
         to_dict({"Job": plan.Job,
                  "Alloc": [a for placed in plan.NodeAllocation.values()
                            for a in placed]})),
        use_bin_type=True)
    return msgpack.unpackb(blob, raw=False)


def drain(sub, idle=0.3, timeout=15):
    """Pop frames until the stream goes idle. The callers quiesce the
    workload first, so idle == drained."""
    frames = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        frame = sub.next(timeout=idle)
        if frame is None:
            if frames or sub.status()[0]:
                break
            continue
        assert "Dropped" not in frame, "oracle subscriber overflowed"
        frames.append(frame)
    return frames


def fold(frames):
    """Fold an event stream into shadow state: the consumer contract —
    summaries carry enough to reconstruct membership and placement."""
    s = types.SimpleNamespace(nodes={}, jobs={}, evals={}, allocs={},
                              services={}, batch_events=0)
    last = 0
    for frame in frames:
        assert frame["Index"] > last, "frames out of raft-index order"
        last = frame["Index"]
        for ev in frame["Events"]:
            _fold_one(s, ev)
    return s


def _fold_one(s, ev):
    t, p = ev["Type"], ev["Payload"]
    if t in ("NodeRegistered", "NodeStatusUpdated"):
        s.nodes[p["ID"]] = p["Status"]
    elif t == "NodeDeregistered":
        s.nodes.pop(p["ID"], None)
    elif t == "NodeDrainUpdated":
        pass
    elif t in ("JobRegistered", "PeriodicLaunchUpserted"):
        if t == "JobRegistered":
            s.jobs[p["ID"]] = p
    elif t in ("JobDeregistered", "PeriodicLaunchDeleted"):
        if t == "JobDeregistered":
            s.jobs.pop(p["ID"], None)
    elif t == "EvalUpdated":
        s.evals[p["ID"]] = p["Status"]
    elif t == "EvalDeleted":
        s.evals.pop(p["ID"], None)
    elif t in ("AllocUpdated", "AllocPlaced"):
        cur = s.allocs.setdefault(p["ID"], {})
        cur.update({k: v for k, v in p.items() if v != ""})
    elif t == "AllocClientUpdated":
        cur = s.allocs.get(p["ID"])
        if cur is not None:
            cur["ClientStatus"] = p["ClientStatus"]
            cur["DesiredStatus"] = p["DesiredStatus"]
    elif t == "AllocDeleted":
        s.allocs.pop(p["ID"], None)
    elif t == "AllocationBatchCommitted":
        s.batch_events += 1
        for row in expand_batch(ev):
            _fold_one(s, row)
    elif t == "ServiceRegistered":
        s.services[p["ID"]] = p
    elif t == "ServiceDeregistered":
        s.services.pop(p["ID"], None)
    else:
        raise AssertionError(f"fold has no rule for event type {t!r}")


def placement_map(state):
    return {a.ID: (a.JobID, a.NodeID) for a in state.allocs()}


# ----------------------------------------------------------------- gates

class TestCommitPathParity:
    def test_sweep_publishes_one_batch_event_matching_columns(self):
        """A 16-alloc system sweep is ONE AllocationBatch event whose
        descriptor names exactly the committed rows — no per-alloc
        materialization on the publish path."""
        job, plan = sweep_plan()
        fsm = fsm_with_broker()
        sub = fsm.events.subscribe(from_index=0)
        msg, payload = columnar_entry(plan)
        fsm.apply(APPLY_INDEX, MessageType(msg), payload)
        frame = sub.next(timeout=1)
        batch = [e for e in frame["Events"]
                 if e["Topic"] == "AllocationBatch"]
        assert len(batch) == 1
        p = batch[0]["Payload"]
        sweep = plan._sweep
        assert p["Count"] == len(sweep.alloc_ids) == sum(p["Counts"])
        assert p["AllocIDs"] == list(sweep.alloc_ids)
        assert p["Kind"] == "system"
        assert set(p["AllocIDs"]) \
            == {a.ID for a in fsm.state.allocs_by_job(job.ID)}

    def test_fanout_fold_matches_store_on_both_paths(self):
        """The same sweep committed columnar (fan-out expanded at read
        time) and per-object folds to the SAME shadow placements, and
        both match their stores exactly."""
        job, plan = sweep_plan()
        folds = {}
        for path, entry in (("columnar", columnar_entry(plan)),
                            ("object", object_entry(plan))):
            fsm = fsm_with_broker()
            sub = fsm.events.subscribe(from_index=0, fanout=True)
            msg, payload = entry
            fsm.apply(APPLY_INDEX, MessageType(msg), payload)
            shadow = fold(drain(sub, idle=0.05, timeout=2))
            got = {aid: (d["JobID"], d["NodeID"])
                   for aid, d in shadow.allocs.items()}
            assert got == placement_map(fsm.state), path
            folds[path] = got
        assert folds["columnar"] == folds["object"]

    def test_service_window_batch_event_is_service_kind(self):
        """The pipelined service fast path's columnar commit publishes
        its batch event with Kind=service and the same descriptor
        parity."""
        ns = service_window(svc_job())
        assert ns.ok and not ns.failed
        fsm = fsm_with_broker()
        sub = fsm.events.subscribe(from_index=0, fanout=True)
        raw = fsm.events.subscribe(from_index=0)
        msg, payload = columnar_entry(ns.plan)
        fsm.apply(APPLY_INDEX, MessageType(msg), payload)
        shadow = fold(drain(sub, idle=0.05, timeout=2))
        # The un-expanded stream carries exactly ONE batch event...
        frame = raw.next(timeout=1)
        assert [e["Payload"]["Kind"] for e in frame["Events"]
                if e["Topic"] == "AllocationBatch"] == ["service"]
        # ...and its fan-out expansion folds to the store's placements.
        got = {aid: (d["JobID"], d["NodeID"])
               for aid, d in shadow.allocs.items()}
        assert got == placement_map(fsm.state)
        assert all(d["Kind"] == "service" for d in shadow.allocs.values())


def _storm_server(columnar=True, event_buffer_size=4096):
    return Server(ServerConfig(num_schedulers=1, scheduler_window=8,
                               service_columnar=columnar,
                               event_buffer_size=event_buffer_size,
                               min_heartbeat_ttl=3600.0,
                               heartbeat_grace=3600.0))


def _wait_complete(srv, eval_ids, timeout=30):
    wait_for(lambda: all(
        (e := srv.state.eval_by_id(eid)) is not None
        and e.Status == EvalStatusComplete for eid in eval_ids),
        timeout=timeout, msg="storm evals never completed")


class TestLiveStormOracle:
    @pytest.mark.parametrize("columnar", [True, False])
    def test_storm_fold_matches_store(self, columnar):
        """A live service storm through a real server — placements, a
        deregister's evictions, eval lifecycle — folds from the event
        stream into exactly the store's membership, on BOTH service
        commit paths (columnar batch events vs per-object updates)."""
        srv = _storm_server(columnar=columnar)
        srv.establish_leadership()
        try:
            broker = srv.fsm.events
            sub = broker.subscribe(from_index=0, fanout=True,
                                   queue_size=100_000)
            for i in range(6):
                srv.node_register(make_node(i))
            jobs = [svc_job() for _ in range(4)]
            eval_ids = [srv.job_register(j)[0] for j in jobs]
            _wait_complete(srv, eval_ids)
            # Deregister one job: its evictions must stream as
            # per-object updates on either path.
            dereg_eval, _ = srv.job_deregister(jobs[0].ID)
            _wait_complete(srv, [dereg_eval])
            state = srv.state
            wait_for(lambda: broker.stats()["Tail"]
                     >= state.latest_index(), timeout=10)
            shadow = fold(drain(sub))

            assert set(shadow.nodes) == {n.ID for n in state.nodes()}
            assert set(shadow.jobs) == {j.ID for j in state.jobs()}
            assert {aid: v[1] for aid, v in placement_map(state).items()} \
                == {aid: d["NodeID"] for aid, d in shadow.allocs.items()}
            store_evals = {e.ID: e.Status for e in state.evals()}
            assert shadow.evals == store_evals
            # Desired-status agreement: the deregistered job's allocs
            # fold to the same terminal intent the store holds.
            for a in state.allocs():
                if a.JobID == jobs[0].ID:
                    assert shadow.allocs[a.ID]["DesiredStatus"] \
                        == a.DesiredStatus
            # Path check: batch-expanded rows (they alone carry the
            # descriptor's Kind marker) iff the columnar path committed.
            batch_rows = [aid for aid, d in shadow.allocs.items()
                          if "Kind" in d]
            batches = state.columnar_stats()["Batches"]
            if columnar:
                assert batch_rows
                assert batches.get("service", 0) >= 1
            else:
                assert not batch_rows
                assert not batches
        finally:
            srv.shutdown()

    def test_events_disabled_is_free_and_bit_identical(self):
        """The same fixed system storm with the broker off: NO broker
        object exists (the apply path pays one attribute check), and
        placements are bit-identical to the armed run."""
        def run(event_buffer_size):
            srv = _storm_server(event_buffer_size=event_buffer_size)
            srv.establish_leadership()
            try:
                for i in range(6):
                    srv.node_register(make_node(i))
                eval_ids = []
                for k in range(3):
                    job = sys_job(count=1)  # system jobs validate count=1
                    job.ID = f"ev-storm-{k}"
                    job.Name = job.ID
                    job.init_fields()
                    eval_ids.append(srv.job_register(job)[0])
                _wait_complete(srv, eval_ids)
                placements = sorted((a.JobID, a.Name, a.NodeID)
                                    for a in srv.state.allocs())
                return placements, srv.fsm.events
            finally:
                srv.shutdown()

        armed, broker = run(4096)
        disarmed, no_broker = run(0)
        assert broker is not None and no_broker is None
        assert armed == disarmed
        assert armed  # the storm really placed

    def test_publish_drop_is_fsm_invisible_but_fold_visible(self):
        """The events.publish failpoint's drop mode: state commits
        perfectly (never FSM-visible), stream coverage advances with no
        gap error — and the ONLY detector is this fold, which comes up
        short exactly one entry."""
        fsm = fsm_with_broker()
        sub = fsm.events.subscribe(from_index=0)
        failpoints.arm_from_spec("events.publish=drop:count=1")
        lost, kept = make_node(0), make_node(1)
        fsm.apply(1, MessageType.NodeRegister, {"Node": to_dict(lost)})
        fsm.apply(2, MessageType.NodeRegister, {"Node": to_dict(kept)})
        shadow = fold(drain(sub, idle=0.05, timeout=2))
        store_nodes = {n.ID for n in fsm.state.nodes()}
        assert store_nodes == {lost.ID, kept.ID}  # FSM never saw it
        assert set(shadow.nodes) == {kept.ID}  # the fold did
        assert fsm.events.stats()["Tail"] == 2  # coverage advanced
        # And a late subscriber replays without a gap error — the loss
        # is silent at the ring level, by design.
        late = fsm.events.subscribe(from_index=0)
        assert late.next(timeout=1)["Index"] == 2
