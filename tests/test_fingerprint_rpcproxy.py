"""Cloud-env fingerprints, periodic re-fingerprinting, and rpcproxy
rebalance (reference: client/fingerprint/env_aws.go, fingerprint.go:68-77,
client/rpcproxy/rpcproxy.go:317-449)."""

import http.server
import threading
import time

from nomad_tpu import mock
from nomad_tpu.client.fingerprint import (
    _env_aws,
    _env_gce,
    fingerprint_node,
    run_periodic_fingerprints,
)
from nomad_tpu.client.rpc import RpcProxy


class _AWSMeta(http.server.BaseHTTPRequestHandler):
    DATA = {
        "/ami-id": "ami-1234",
        "/instance-id": "i-abcdef",
        "/instance-type": "m4.large",
        "/local-ipv4": "10.0.0.7",
        "/placement/availability-zone": "us-west-2a",
    }

    def do_GET(self):
        value = self.DATA.get(self.path)
        if value is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(value.encode())

    def log_message(self, *args):
        pass


class _GCEMeta(_AWSMeta):
    DATA = {
        "/instance/id": "7777",
        "/instance/machine-type":
            "projects/1/machineTypes/n1-standard-2",
        "/instance/zone": "projects/1/zones/us-central1-a",
        "/instance/hostname": "vm.c.proj.internal",
    }

    def do_GET(self):
        if self.headers.get("Metadata-Flavor") != "Google":
            self.send_response(403)
            self.end_headers()
            return
        super().do_GET()


def _serve(handler):
    srv = http.server.HTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class _Config:
    def __init__(self, **options):
        self.options = options
        self.alloc_dir = "/tmp"
        self.network_speed = 0

    def read_option(self, key, default=""):
        return self.options.get(key, default)


class TestEnvFingerprints:
    def test_aws_metadata(self):
        srv = _serve(_AWSMeta)
        try:
            node = mock.node()
            cfg = _Config(**{"fingerprint.env_aws.url":
                             f"http://127.0.0.1:{srv.server_address[1]}/"})
            assert _env_aws(node, cfg)
            assert node.Attributes["platform.aws.ami-id"] == "ami-1234"
            assert node.Attributes["unique.platform.aws.instance-id"] == \
                "i-abcdef"
            assert node.Attributes[
                "platform.aws.placement.availability-zone"] == "us-west-2a"
            assert node.Links["aws.ec2"] == "us-west-2a.i-abcdef"
        finally:
            srv.shutdown()

    def test_gce_metadata_requires_header_and_trims_paths(self):
        srv = _serve(_GCEMeta)
        try:
            node = mock.node()
            cfg = _Config(**{"fingerprint.env_gce.url":
                             f"http://127.0.0.1:{srv.server_address[1]}/"})
            assert _env_gce(node, cfg)
            assert node.Attributes["platform.gce.machine-type"] == \
                "n1-standard-2"
            assert node.Attributes["platform.gce.zone"] == "us-central1-a"
            assert node.Links["gce"] == "us-central1-a.7777"
        finally:
            srv.shutdown()

    def test_not_on_cloud_is_clean_false(self):
        node = mock.node()
        cfg = _Config(**{"fingerprint.env_aws.url":
                         "http://127.0.0.1:1/"})
        assert _env_aws(node, cfg) is False
        assert "platform.aws.ami-id" not in node.Attributes


class TestPeriodicFingerprint:
    def test_material_change_detected(self):
        node = mock.node()
        fingerprint_node(node, _Config())
        # No change on an immediate re-run (free-space drift is suppressed).
        assert run_periodic_fingerprints(node, _Config()) is False
        # A materially different reading (simulate: wipe the attr) reports.
        node.Attributes["unique.storage.bytesfree"] = "1"
        assert run_periodic_fingerprints(node, _Config()) is True


class TestRpcProxyRebalance:
    def test_rebalance_promotes_healthy(self):
        proxy = RpcProxy(["dead1:1", "dead2:1", "alive:1"])
        chosen = proxy.rebalance(lambda addr: addr.startswith("alive"))
        assert chosen == "alive:1"
        assert proxy.find_server() == "alive:1"
        assert set(proxy.servers()) == {"dead1:1", "dead2:1", "alive:1"}

    def test_rebalance_all_dead(self):
        proxy = RpcProxy(["a:1", "b:1"])
        assert proxy.rebalance(lambda addr: False) is None

    def test_single_server_noop(self):
        proxy = RpcProxy(["only:1"])
        assert proxy.rebalance(lambda a: False) == "only:1"
