"""Property-based round-trips for the wire codec, the job diff, and the
HCL frontend (reference test frame: nomad/structs/structs_test.go codec
round-trips, diff_test.go's 2.8k-line case grid, jobspec/parse_test.go —
generator-driven here instead of hand-enumerated).

Three properties:
  1. codec: msgpack encode -> decode is the identity on randomized
     Job/Node/Allocation/Evaluation trees (compared via to_dict).
  2. diff: job_diff(a, a) is empty; single randomized field edits
     produce exactly the expected FieldDiff; add/remove of task groups
     and tasks classify Added/Deleted; and against a naive deep-compare
     oracle, the diff is non-empty iff the diffed surfaces differ.
  3. HCL: a generated job spec rendered to HCL text (escapes, heredocs,
     blocks) parses back to the generating values.

Hypothesis runs a fixed-seed deterministic profile in CI (derandomize):
failures reproduce; the generator space still covers hundreds of cases
per run.
"""

import dataclasses
import string

import pytest

# The property suite is hypothesis-driven; without the library the module
# must SKIP cleanly, not error the whole collection run.
pytest.importorskip("hypothesis")

from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from nomad_tpu import mock
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Evaluation,
    Job,
    NetworkResource,
    Node,
    Port,
    Resources,
)
from nomad_tpu.structs.codec import decode, encode, to_dict
from nomad_tpu.structs.diff import (
    DiffTypeAdded,
    DiffTypeDeleted,
    DiffTypeEdited,
    DiffTypeNone,
    _JOB_FILTER,
    job_diff,
)

SETTINGS = settings(max_examples=120, deadline=None, derandomize=True,
                    suppress_health_check=[HealthCheck.too_slow])

_NAME = st.text(string.ascii_lowercase + string.digits + "-", min_size=1,
                max_size=12)
_TEXT = st.text(min_size=0, max_size=24)  # full unicode for wire fields
_SMALL = st.integers(min_value=0, max_value=1 << 30)


def _ports(label_prefix):
    return st.lists(
        st.builds(Port, Label=_NAME.map(lambda s: label_prefix + s),
                  Value=st.integers(min_value=1, max_value=65535)),
        max_size=2, unique_by=lambda p: p.Label)


_resources = st.builds(
    Resources,
    CPU=st.integers(min_value=20, max_value=8000),
    MemoryMB=st.integers(min_value=10, max_value=16384),
    DiskMB=st.integers(min_value=10, max_value=10000),
    IOPS=_SMALL,
    Networks=st.lists(
        st.builds(NetworkResource, IP=_TEXT, MBits=_SMALL,
                  ReservedPorts=_ports("r"), DynamicPorts=_ports("d")),
        max_size=2))

_constraints = st.lists(
    st.builds(Constraint, LTarget=_TEXT, RTarget=_TEXT,
              Operand=st.sampled_from(["=", "!=", "version", "regexp"])),
    max_size=3)


@st.composite
def jobs(draw):
    """A mock job with randomized wire-visible fields: enough structural
    freedom to exercise every codec path (nested dataclasses, lists,
    maps, unicode) while staying a plausible Job."""
    job = mock.job()
    job.ID = draw(_NAME)
    job.Name = draw(_TEXT)
    job.Region = draw(_NAME)
    job.Priority = draw(st.integers(min_value=1, max_value=100))
    job.AllAtOnce = draw(st.booleans())
    job.Datacenters = draw(st.lists(_NAME, min_size=1, max_size=3))
    job.Constraints = draw(_constraints)
    job.Meta = draw(st.dictionaries(_NAME, _TEXT, max_size=3))
    for gi, tg in enumerate(job.TaskGroups):
        tg.Name = f"g{gi}-" + draw(_NAME)
        tg.Count = draw(st.integers(min_value=1, max_value=50))
        tg.Meta = draw(st.dictionaries(_NAME, _TEXT, max_size=2))
        for ti, task in enumerate(tg.Tasks):
            task.Name = f"t{ti}-" + draw(_NAME)
            task.Resources = draw(_resources)
            task.Env = draw(st.dictionaries(_NAME, _TEXT, max_size=3))
            task.Services = []
    return job


@st.composite
def nodes(draw):
    node = mock.node()
    node.ID = draw(_NAME)
    node.Datacenter = draw(_NAME)
    node.Attributes = draw(st.dictionaries(_NAME, _TEXT, max_size=4))
    node.Meta = draw(st.dictionaries(_NAME, _TEXT, max_size=4))
    node.Resources = draw(_resources)
    node.Reserved = draw(_resources)
    node.Status = draw(st.sampled_from(["initializing", "ready", "down"]))
    return node


@st.composite
def allocs(draw):
    alloc = mock.alloc()
    alloc.ID = draw(_NAME)
    alloc.Name = draw(_TEXT)
    alloc.TaskResources = draw(
        st.dictionaries(_NAME, _resources, max_size=2))
    alloc.DesiredStatus = draw(st.sampled_from(["run", "stop", "evict"]))
    alloc.ClientStatus = draw(
        st.sampled_from(["pending", "running", "complete", "failed"]))
    return alloc


class TestCodecRoundTrip:
    @SETTINGS
    @given(jobs())
    def test_job_identity(self, job):
        assert to_dict(decode(Job, encode(job))) == to_dict(job)

    @SETTINGS
    @given(nodes())
    def test_node_identity(self, node):
        assert to_dict(decode(Node, encode(node))) == to_dict(node)

    @SETTINGS
    @given(allocs())
    def test_alloc_identity(self, alloc):
        assert to_dict(decode(Allocation, encode(alloc))) == to_dict(alloc)

    @SETTINGS
    @given(st.builds(Evaluation, ID=_NAME, Type=_TEXT, Priority=_SMALL,
                     JobID=_NAME, Status=_TEXT,
                     ClassEligibility=st.dictionaries(_NAME, st.booleans(),
                                                      max_size=3)))
    def test_eval_identity(self, ev):
        assert to_dict(decode(Evaluation, encode(ev))) == to_dict(ev)


def _naive_differs(a, b):
    """Deep-compare oracle over the diffed surface: to_dict equality with
    every key the diff itself filters removed — the job-level bookkeeping
    keys (_JOB_FILTER) and the NetworkResource keys diff.py:232 excludes
    (Device/CIDR/IP are runtime-assigned, not spec)."""
    def scrub(d):
        for k in _JOB_FILTER:
            d.pop(k, None)
        for tg in d.get("TaskGroups") or []:
            for task in tg.get("Tasks") or []:
                res = task.get("Resources") or {}
                for net in res.get("Networks") or []:
                    for k in ("Device", "CIDR", "IP"):
                        net.pop(k, None)
        return d

    return scrub(to_dict(a)) != scrub(to_dict(b))


class TestDiffProperties:
    @SETTINGS
    @given(jobs())
    def test_self_diff_is_none(self, job):
        d = job_diff(job, job)
        assert d.Type == DiffTypeNone
        assert not d.Fields
        assert all(tg.Type == DiffTypeNone for tg in d.TaskGroups)

    @SETTINGS
    @given(jobs(), st.data())
    def test_single_scalar_edit_is_reported_exactly(self, job, data):
        new = job.copy()  # independent deep copy
        field_name, value = data.draw(st.sampled_from([
            ("Priority", job.Priority + 1),
            ("Region", job.Region + "x"),
            ("AllAtOnce", not job.AllAtOnce),
            ("Type", job.Type + "x"),
        ]))
        setattr(new, field_name, value)
        d = job_diff(job, new)
        assert d.Type == DiffTypeEdited
        edited = [f for f in d.Fields if f.Type != DiffTypeNone]
        assert [f.Name for f in edited] == [field_name]
        assert edited[0].Old != edited[0].New

    @SETTINGS
    @given(jobs(), _NAME)
    def test_group_add_remove_classified(self, job, name):
        new = job.copy()
        extra = job.copy().TaskGroups[0]
        extra.Name = "zz-" + name
        new.TaskGroups.append(extra)
        d = job_diff(job, new)
        added = [tg for tg in d.TaskGroups if tg.Type == DiffTypeAdded]
        assert [tg.Name for tg in added] == ["zz-" + name]

        removed = job.copy()
        gone = removed.TaskGroups.pop(0)
        d2 = job_diff(job, removed)
        deleted = [tg for tg in d2.TaskGroups if tg.Type == DiffTypeDeleted]
        assert [tg.Name for tg in deleted] == [gone.Name]

    @SETTINGS
    @given(jobs(), jobs())
    def test_nonempty_iff_oracle_differs(self, a, b):
        b.ID = a.ID  # diffable pair
        d = job_diff(a, b)
        is_empty = (d.Type == DiffTypeNone and not d.Fields
                    and all(tg.Type == DiffTypeNone for tg in d.TaskGroups)
                    and all(o.Type == DiffTypeNone for o in d.Objects))
        assert is_empty == (not _naive_differs(a, b))


def _hcl_quote(s: str) -> str:
    return '"' + (s.replace("\\", "\\\\").replace('"', '\\"')
                  .replace("\n", "\\n").replace("\t", "\\t")) + '"'


_HCL_TEXT = st.text(
    alphabet=string.printable, min_size=0, max_size=20).map(
        lambda s: s.replace("\r", ""))


class TestHCLRoundTrip:
    @SETTINGS
    @given(job_id=_NAME, dc=_NAME, group=_NAME, task=_NAME,
           meta_val=_HCL_TEXT, env_val=_HCL_TEXT,
           count=st.integers(min_value=1, max_value=99),
           cpu=st.integers(min_value=20, max_value=9999),
           prio=st.integers(min_value=1, max_value=100))
    def test_rendered_spec_parses_to_generating_values(
            self, job_id, dc, group, task, meta_val, env_val, count, cpu,
            prio):
        """Render a job spec with randomized identifiers and string
        values (quotes, backslashes, control chars via escapes) and
        assert the parser recovers the exact generating values."""
        from nomad_tpu.jobspec import parse_job

        text = f'''
job {_hcl_quote(job_id)} {{
  datacenters = [{_hcl_quote(dc)}]
  priority = {prio}
  meta {{ mk = {_hcl_quote(meta_val)} }}
  group {_hcl_quote(group)} {{
    count = {count}
    task {_hcl_quote(task)} {{
      driver = "raw_exec"
      config {{ command = "/bin/true" }}
      env {{ EV = {_hcl_quote(env_val)} }}
      resources {{ cpu = {cpu} memory = 32 disk = 300 }}
    }}
  }}
}}'''
        job = parse_job(text)
        assert job.ID == job_id
        assert job.Datacenters == [dc]
        assert job.Priority == prio
        assert job.Meta["mk"] == meta_val
        tg = job.TaskGroups[0]
        assert tg.Name == group and tg.Count == count
        t = tg.Tasks[0]
        assert t.Name == task
        assert t.Env["EV"] == env_val
        assert t.Resources.CPU == cpu

    @SETTINGS
    @given(body=st.text(alphabet=string.printable, min_size=0,
                        max_size=60).map(lambda s: s.replace("\r", "")))
    def test_heredoc_preserves_multiline_body(self, body):
        from hypothesis import assume

        from nomad_tpu.jobspec import parse_job

        # A heredoc body is raw text: its lines must not collide with the
        # terminator and must themselves be newline-clean fragments.
        assume("EOT" not in body)
        text = f'''
job "h" {{
  datacenters = ["dc1"]
  group "g" {{
    task "t" {{
      driver = "raw_exec"
      config {{ command = "/bin/true" }}
      meta {{ blob = <<EOT
{body}
EOT
      }}
      resources {{ cpu = 20 memory = 32 disk = 300 }}
    }}
  }}
}}'''
        job = parse_job(text)
        parsed = job.TaskGroups[0].Tasks[0].Meta["blob"]
        # The heredoc terminator regex consumes '\n\s*EOT', so a trailing
        # whitespace-only line merges into the terminator: compare modulo
        # trailing whitespace (leading/interior whitespace must survive).
        assert parsed.rstrip() == body.rstrip()
