"""Cross-replica state-digest verification (analysis/replica_digest.py +
the fsm/raft wiring): the chain is canonical and deterministic, readback
effects catch silent store corruption within one checkpoint interval,
snapshots reseed the chain, divergence raises the typed error, and a
replicated 3-node cluster detects an injected follower corruption and
recovers via quarantine + reinstall.
"""

import copy

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.analysis.replica_digest import (
    ReplicaDigest,
    ReplicaDivergenceError,
    chaos_corrupt,
    effect_of,
)
from nomad_tpu.resilience import failpoints
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.structs import to_dict


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


def _node_payloads(n, prefix="n"):
    out = []
    for i in range(n):
        node = mock.node()
        node.ID = f"{prefix}{i}"
        out.append({"Node": to_dict(node)})
    return out


def _replay(payloads, interval=16):
    fsm = FSM()
    fsm.digest = ReplicaDigest(interval=interval)
    for i, p in enumerate(payloads, start=1):
        fsm.apply(i, MessageType.NodeRegister, copy.deepcopy(p))
    return fsm


# ----------------------------------------------------------- chain basics
def test_chain_is_deterministic_across_replicas():
    payloads = _node_payloads(40)
    a, b = _replay(payloads), _replay(payloads)
    assert a.digest.stats()["Chain"] == b.digest.stats()["Chain"]
    assert a.digest.checkpoint() == b.digest.checkpoint() is not None


def test_chain_differs_when_any_effect_differs():
    payloads = _node_payloads(40)
    a = _replay(payloads)
    mutated = copy.deepcopy(payloads)
    mutated[20]["Node"]["Status"] = "down"
    b = _replay(mutated)
    assert a.digest.stats()["Chain"] != b.digest.stats()["Chain"]


def test_checkpoints_land_on_interval_buckets_and_stay_bounded():
    d = ReplicaDigest(interval=10)
    for i in range(1, 201):
        d.fold(i, 0, ("effect", i))
    cps = d.stats()["Checkpoints"]
    assert len(cps) <= 8
    assert all(idx % 10 == 0 for idx in cps)
    idx, hexv = d.checkpoint()
    assert idx == 200 and cps[200] == hexv


def test_verify_matches_skips_and_diverges():
    payloads = _node_payloads(40)
    a, b = _replay(payloads), _replay(payloads)
    idx, hexv = a.digest.checkpoint()
    assert b.digest.verify(idx, hexv) is True
    # Re-verifying the same checkpoint is a skip, not a second compare.
    assert b.digest.verify(idx, hexv) is None
    # An index we never folded to a checkpoint is a skip.
    assert b.digest.verify(idx + 7, "00" * 16) is None
    c = _replay(payloads)
    with pytest.raises(ReplicaDivergenceError) as exc:
        c.digest.verify(idx, "00" * 16)
    assert exc.value.index == idx
    assert c.digest.stats()["Diverged"] == 1


def test_unsynced_digest_never_alarms():
    d = ReplicaDigest(interval=4)
    for i in range(1, 9):
        d.fold(i, 0, i)
    d.mark_unsynced("test")
    assert d.verify(8, "00" * 16) is None
    assert d.checkpoint() is None  # and never exports one either


# ------------------------------------------------------ canonical encoder
def test_encoder_distinguishes_types_and_orders_dicts():
    def chain(effect):
        d = ReplicaDigest()
        d.fold(1, 0, effect)
        return d.stats()["Chain"]

    assert chain({"a": 1, "b": 2}) == chain({"b": 2, "a": 1})
    assert chain(1) != chain("1") != chain(1.0)
    assert chain(None) != chain(0) != chain(False)
    assert chain([1, 2]) != chain([2, 1])
    arr = np.arange(6, dtype=np.int64)
    assert chain(arr) == chain(arr.copy())
    assert chain(arr) != chain(arr.astype(np.int32))
    assert chain(arr) != chain(arr.reshape(2, 3))


# ------------------------------------------------------- effect readbacks
def test_effect_readback_sees_silent_store_corruption():
    """The digest folds what the STORE says, not what the payload says —
    an in-place corruption lands in the chain within one fold."""
    payloads = _node_payloads(20)
    a, b = _replay(payloads), _replay(payloads)
    ev = mock.eval()
    req = {"Evals": [to_dict(ev)]}
    a.apply(21, MessageType.EvalUpdate, copy.deepcopy(req))
    b.apply(21, MessageType.EvalUpdate, copy.deepcopy(req))
    assert a.digest.stats()["Chain"] == b.digest.stats()["Chain"]
    # Corrupt b's store the way the chaos failpoint does, then apply one
    # more (clean) entry touching the corrupt row on both replicas.
    assert chaos_corrupt(b.state, 22, int(MessageType.EvalUpdate), req)
    follow = {"Evals": [to_dict(ev)]}
    ea = effect_of(a.state, 22, int(MessageType.EvalUpdate), follow)
    eb = effect_of(b.state, 22, int(MessageType.EvalUpdate), follow)
    assert ea != eb  # readback, not payload echo


def test_sweep_effect_digests_columns_without_materializing(monkeypatch):
    fsm = FSM()
    fsm.digest = ReplicaDigest(interval=4)
    node = mock.node()
    fsm.apply(1, MessageType.NodeRegister, {"Node": to_dict(node)})
    job = mock.system_job()
    tmpl = mock.alloc()
    tmpl.NodeID = node.ID
    tmpl.JobID, tmpl.Job = job.ID, job
    sweep = {"Templates": [to_dict(tmpl)], "TGIdx": [0, 0],
             "AllocIDs": ["a1", "a2"], "Names": ["w.g[0]", "w.g[1]"],
             "RowNodeIDs": [node.ID], "Counts": [2], "Rows": [0, 0],
             "Delta": np.zeros((1, 4), dtype=np.float32)}
    calls = []
    monkeypatch.setattr(fsm.state, "alloc_by_id",
                        lambda aid: calls.append(aid))
    effect = effect_of(fsm.state, 2, int(MessageType.ApplySweepBatch),
                       {"Batch": [{"Job": to_dict(job), "Sweep": sweep}]})
    assert calls == []  # columns digested directly, no per-row readback
    assert effect[0] == "sweep"
    d1, d2 = ReplicaDigest(), ReplicaDigest()
    d1.fold(2, 13, effect)
    d2.fold(2, 13, effect_of(fsm.state, 2, 13,
                             {"Batch": [{"Job": to_dict(job),
                                         "Sweep": dict(sweep)}]}))
    assert d1.stats()["Chain"] == d2.stats()["Chain"]


# ----------------------------------------------------------- fsm wiring
def test_snapshot_reseeds_the_chain_canonically():
    payloads = _node_payloads(50)
    a = _replay(payloads)
    snap = a.snapshot()
    b = FSM()
    b.digest = ReplicaDigest(interval=16)
    b.restore(snap)
    assert b.digest.stats()["Chain"] == a.digest.stats()["Chain"]
    # Folding the same next entry keeps the chains equal: canonical.
    extra = _node_payloads(1, prefix="x")[0]
    a.apply(51, MessageType.NodeRegister, copy.deepcopy(extra))
    b.apply(51, MessageType.NodeRegister, copy.deepcopy(extra))
    assert b.digest.stats()["Chain"] == a.digest.stats()["Chain"]


def test_snapshot_without_digest_enters_unverified_mode():
    a = _replay(_node_payloads(10))
    snap = a.snapshot()
    snap.pop("digest")
    b = FSM()
    b.digest = ReplicaDigest(interval=4)
    b.restore(snap)
    st = b.digest.stats()
    assert not st["Synced"] and "without" in st["UnsyncedReason"]
    assert b.digest.verify(8, "00" * 16) is None


def test_fold_failure_is_contained_and_marks_unsynced():
    failpoints.arm("fsm.digest.mutate", "error", count=1)
    fsm = _replay(_node_payloads(3))
    # All three entries applied despite the injected fold failure...
    assert len(fsm.state.nodes()) == 3
    st = fsm.digest.stats()
    assert not st["Synced"] and st["Folds"] == 2


def test_divergence_detected_within_one_interval():
    """Corruption at index i must surface at the FIRST checkpoint at or
    after i — within `interval` applies, the ISSUE's K bound."""
    interval = 8
    payloads = _node_payloads(32)
    leader = _replay(payloads, interval=interval)
    leader_cps = leader.digest.stats()["Checkpoints"]
    follower = FSM()
    follower.digest = ReplicaDigest(interval=interval)
    corrupt_at = 12
    detected = None
    for i, p in enumerate(payloads, start=1):
        if i == corrupt_at:
            # The armed seam corrupts THIS entry's just-written row
            # before the effect readback (a bare FSM has no leader-side
            # observers, so the non-leader gate passes).
            failpoints.arm("fsm.digest.mutate", "drop", count=1)
        follower.apply(i, MessageType.NodeRegister, copy.deepcopy(p))
        if i in leader_cps:
            try:
                follower.digest.verify(i, leader_cps[i])
                assert i < corrupt_at, \
                    "checkpoint after the corruption verified clean"
            except ReplicaDivergenceError:
                detected = i
                break
    assert detected is not None
    assert detected - corrupt_at <= interval


# ----------------------------------------------------- replicated cluster
def test_cluster_detects_and_recovers_from_follower_corruption():
    """3-node replicated cluster: corrupt one follower's store via the
    armed seam; the digest exchange must detect it (diverged metric),
    quarantine the follower, and reconverge every replica onto the
    leader's verified state."""
    from nomad_tpu.raft import RaftConfig
    from nomad_tpu.rpc.cluster import ClusterServer
    from nomad_tpu.server.server import ServerConfig

    from helpers import wait_for

    fast = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                      election_timeout_max=0.16, apply_timeout=5.0,
                      snapshot_threshold=30, trailing_logs=32)
    nodes = []
    try:
        for i in range(3):
            cs = ClusterServer(ServerConfig(
                node_id="", num_schedulers=0, digest_interval=16))
            nodes.append(cs)
        addrs = [cs.addr for cs in nodes]
        for cs in nodes:
            cs.connect(addrs, raft_config=fast)
            cs.start()
        assert wait_for(
            lambda: any(cs.server.is_leader() for cs in nodes), timeout=30)
        leader = next(cs for cs in nodes if cs.server.is_leader())

        def apply_nodes(n, prefix):
            for i in range(n):
                node = mock.node()
                node.ID = f"{prefix}{i}"
                leader.server.raft.apply(MessageType.NodeRegister,
                                         {"Node": node})

        def diverged_total():
            return sum(cs.server.fsm.digest.stats()["Diverged"]
                       for cs in nodes)

        apply_nodes(40, "warm")
        assert diverged_total() == 0  # zero false positives warm
        # One corruption on whichever follower applies next.
        failpoints.arm("fsm.digest.mutate", "drop", count=1)
        apply_nodes(40, "storm")
        assert wait_for(lambda: diverged_total() >= 1,
                        timeout=30, msg="divergence never detected")
        failpoints.disarm_all()
        apply_nodes(10, "heal")

        def converged():
            want = {n.ID for n in leader.server.state.nodes()}
            return all(
                {n.ID for n in cs.server.state.nodes()} == want
                for cs in nodes)

        assert wait_for(converged, timeout=60, interval=0.25,
                        msg="replicas reconverged after quarantine")
        # The corruption marker must not survive anywhere.
        for cs in nodes:
            assert all(n.Status != "chaos-diverged"
                       for n in cs.server.state.nodes())
    finally:
        for cs in nodes:
            try:
                cs.shutdown()
            except Exception:
                pass
