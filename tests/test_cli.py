"""CLI surface: every user-facing command driven in-process against a live
dev agent through the HTTP API, exactly as `python -m nomad_tpu.cli` would
(reference style: command/*_test.go run each Command against a test agent).
"""

import json
import os

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.cli.commands import main
from nomad_tpu.structs.structs import EvalStatusComplete


from helpers import wait_for  # noqa: E402


@pytest.fixture(scope="module")
def dev_agent():
    a = Agent(AgentConfig(server_enabled=True, client_enabled=True,
                          dev_mode=True, http_port=0, rpc_port=0,
                          serf_port=0, node_name="cli-dev",
                          num_schedulers=1, enable_debug=True,
                          options={"driver.raw_exec.enable": "true"}))
    a.start()
    assert wait_for(lambda: a.server.is_leader() and a.server._leader)
    assert wait_for(lambda: any(n.Status == "ready"
                                for n in a.server.state.nodes()), timeout=30)
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def address(dev_agent):
    return f"http://127.0.0.1:{dev_agent.http.port}"


def run_cli(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr()
    return rc, out.out, out.err


@pytest.fixture(scope="module")
def jobfile(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    path = d / "example.nomad"
    old = os.getcwd()
    os.chdir(d)
    try:
        rc = main(["init"])
        assert rc == 0
        assert path.exists()
    finally:
        os.chdir(old)
    # Shrink the example so it places on the dev node and finishes fast.
    text = path.read_text()
    return str(path), text


class TestJobLifecycle:
    def test_validate_and_plan_and_run(self, capsys, address, jobfile,
                                       dev_agent):
        path, _ = jobfile
        rc, out, _ = run_cli(capsys, "validate", path)
        assert rc == 0

        rc, out, _ = run_cli(capsys, "plan", "-address", address, path)
        assert rc in (0, 1)  # 1 = changes would be made (job is new)
        assert "+ Job" in out or "Job:" in out or out

        rc, out, _ = run_cli(capsys, "run", "-detach", "-address", address,
                             path)
        assert rc == 0, out
        eval_id = out.strip().splitlines()[-1]
        assert wait_for(lambda: (
            (e := dev_agent.server.state.eval_by_id(eval_id)) is not None
            and e.Status == EvalStatusComplete), timeout=60)

    def test_validate_prints_ignored_driver_key_warnings(self, capsys,
                                                         tmp_path):
        """`validate` is offline, so the ignored-config warnings must be
        computed locally — same contract as the register path."""
        path = tmp_path / "priv.nomad"
        path.write_text('''
job "priv" {
  datacenters = ["dc1"]
  group "g" {
    task "t" {
      driver = "docker"
      config { image = "busybox" privileged = true }
      resources { cpu = 20 memory = 16 disk = 300 }
    }
  }
}
''')
        rc, out, err = run_cli(capsys, "validate", str(path))
        assert rc == 0
        assert "privileged" in err and "ignored" in err

    def test_status_inspect_stop(self, capsys, address, jobfile):
        rc, out, _ = run_cli(capsys, "status", "-address", address)
        assert rc == 0 and "example" in out

        rc, out, _ = run_cli(capsys, "status", "-address", address,
                             "example")
        assert rc == 0 and "example" in out

        rc, out, _ = run_cli(capsys, "inspect", "-address", address,
                             "example")
        assert rc == 0
        assert json.loads(out)["Job"]["ID"] == "example"

        rc, out, _ = run_cli(capsys, "stop", "-detach", "-address", address,
                             "example")
        assert rc == 0

    def test_run_output_mode_emits_json(self, capsys, address, jobfile):
        path, _ = jobfile
        rc, out, _ = run_cli(capsys, "run", "-output", path)
        assert rc == 0
        assert json.loads(out)["Job"]["ID"] == "example"


class TestClusterCommands:
    def test_node_status_and_drain(self, capsys, address, dev_agent):
        rc, out, _ = run_cli(capsys, "node-status", "-address", address)
        assert rc == 0 and "ready" in out
        node_id = dev_agent.server.state.nodes()[0].ID

        rc, out, _ = run_cli(capsys, "node-status", "-address", address,
                             node_id[:8])
        assert rc == 0 and node_id in out

        rc, out, _ = run_cli(capsys, "node-drain", "-address", address,
                             "-enable", node_id)
        assert rc == 0
        assert wait_for(lambda: dev_agent.server.state.node_by_id(
            node_id).Drain)
        rc, out, _ = run_cli(capsys, "node-drain", "-address", address,
                             "-disable", node_id)
        assert rc == 0
        assert wait_for(lambda: not dev_agent.server.state.node_by_id(
            node_id).Drain)

    def test_alloc_and_eval_status(self, capsys, address, dev_agent):
        from nomad_tpu import mock

        job = mock.job()
        tg = job.TaskGroups[0]
        tg.Count = 1
        task = tg.Tasks[0]
        task.Driver = "mock_driver"
        task.Config = {"run_for": 60}
        task.Resources.Networks = []
        task.Services = []
        eval_id, _, _ = dev_agent.server.job_register(job)
        assert wait_for(lambda: (
            (e := dev_agent.server.state.eval_by_id(eval_id)) is not None
            and e.Status == EvalStatusComplete), timeout=30)
        alloc = dev_agent.server.state.allocs_by_job(job.ID)[0]

        rc, out, _ = run_cli(capsys, "alloc-status", "-address", address,
                             alloc.ID[:8])
        assert rc == 0 and alloc.ID[:8] in out

        rc, out, _ = run_cli(capsys, "eval-status", "-address", address,
                             eval_id[:8])
        assert rc == 0

    def test_agent_level_commands(self, capsys, address):
        rc, out, _ = run_cli(capsys, "server-members", "-address", address)
        assert rc == 0

        rc, out, _ = run_cli(capsys, "agent-info", "-address", address)
        assert rc == 0 and "nomad" in out.lower()

        rc, out, _ = run_cli(capsys, "system-gc", "-address", address)
        assert rc == 0

        rc, out, _ = run_cli(capsys, "services", "-address", address)
        assert rc == 0

        rc, out, _ = run_cli(capsys, "client-config", "-address", address)
        assert rc == 0

    def test_faults_list_arm_disarm(self, capsys, address):
        """`nomad-tpu faults` drives the failpoint registry end to end
        through the debug-gated HTTP endpoint."""
        from nomad_tpu.resilience import failpoints

        try:
            rc, out, _ = run_cli(capsys, "faults", "-address", address)
            assert rc == 0 and "raft.fsync" in out

            rc, out, _ = run_cli(capsys, "faults", "-address", address,
                                 "gossip.send=drop:count=3")
            assert rc == 0 and "gossip.send" in out

            rc, out, _ = run_cli(capsys, "faults", "-address", address)
            assert rc == 0
            armed_line = next(ln for ln in out.splitlines()
                              if ln.startswith("gossip.send"))
            assert "drop" in armed_line

            rc, out, _ = run_cli(capsys, "faults", "-address", address,
                                 "--disarm-all")
            assert rc == 0 and "disarmed" in out.lower()
            assert failpoints.fire("gossip.send") is None
        finally:
            failpoints.disarm_all()

    def test_sched_stats_prints_pipeline_timers(self, capsys, address):
        """`nomad-tpu sched-stats` surfaces the pipelined worker's stage
        timers/counters (the numbers bench.py prints) via the debug-gated
        endpoint."""
        rc, out, _ = run_cli(capsys, "sched-stats", "-address", address)
        assert rc == 0
        assert "PipelinedWorker" in out
        # Flow counters and at least the headline stage timers show up.
        assert "fast=" in out and "windows=" in out
        for key in ("t_dispatch_ms", "t_collect_ms", "t_drain_fetch_ms"):
            assert key in out

        # Replica-digest health rides the same surface.
        assert "Replica digest:" in out
        assert "diverged=0" in out

        rc, out, _ = run_cli(capsys, "sched-stats", "-address", address,
                             "-json")
        assert rc == 0
        payload = json.loads(out)
        assert payload["Workers"][0]["Stats"]["windows"] >= 0
        assert payload["Digest"]["Diverged"] == 0

    def test_trace_enable_list_show_export_disable(self, capsys, address,
                                                   dev_agent, tmp_path):
        """`nomad-tpu trace` drives the tracing surface end to end:
        runtime enable, a traced register, list, span-tree render, the
        Perfetto export, clear, disable."""
        from nomad_tpu.telemetry import trace

        try:
            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 "-enable", "-ratio", "1.0")
            assert rc == 0 and "enabled" in out

            # One traced mutation through the HTTP API.
            from nomad_tpu.api import Client as APIClient
            from nomad_tpu.jobspec import parse_job

            api = APIClient(address=address)
            job = parse_job('''
job "clitrace" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sh" args = ["-c", "exit 0"] }
      resources { cpu = 20 memory = 16 disk = 300 }
    }
  }
}
''')
            job.init_fields()
            eval_id, _ = api.jobs.register(job)
            assert wait_for(lambda: api.evaluations.info(eval_id)[0]
                            ["Status"] == "complete", timeout=40)

            def listed():
                rc, out, _ = run_cli(capsys, "trace", "-address", address)
                return out if rc == 0 and "rpc.Job.Register" in out else None

            assert wait_for(lambda: listed() is not None, timeout=15,
                            msg="trace list never showed the register")

            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 "-json")
            assert rc == 0
            listing = json.loads(out)
            tid = next(t["TraceID"] for t in listing["Traces"]
                       if t["Root"] == "rpc.Job.Register")

            # Span tree by unique id prefix.
            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 tid[:12])
            assert rc == 0
            assert "rpc.Job.Register" in out and "broker.wait" in out

            # Perfetto export.
            dest = str(tmp_path / "trace.json")
            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 tid, "-export", dest)
            assert rc == 0
            with open(dest) as f:
                payload = json.load(f)
            assert payload["traceEvents"]

            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 "-clear")
            assert rc == 0
            rc, out, _ = run_cli(capsys, "trace", "-address", address,
                                 "-disable")
            assert rc == 0 and "disabled" in out
        finally:
            trace.configure(enabled=False)
            trace.clear()
            run_cli(capsys, "stop", "-detach", "-address", address,
                    "clitrace")

    def test_unknown_job_errors_cleanly(self, capsys, address):
        rc, out, err = run_cli(capsys, "status", "-address", address,
                               "no-such-job")
        assert rc != 0


class TestFsAndMonitor:
    def test_fs_ls_stat_cat_on_live_alloc(self, capsys, address, dev_agent):
        """fs drives the client file API end-to-end: a raw_exec task writes
        stdout, and ls/stat/cat read it through the server->client route."""
        from nomad_tpu import mock

        job = mock.job()
        job.ID = job.Name = "fs-job"
        tg = job.TaskGroups[0]
        tg.Count = 1
        task = tg.Tasks[0]
        task.Name = "echoer"
        task.Driver = "raw_exec"
        task.Config = {"command": "/bin/sh",
                       "args": ["-c", "echo fs-cli-test; sleep 60"]}
        task.Resources.Networks = []
        task.Services = []
        eval_id, _, _ = dev_agent.server.job_register(job)
        assert wait_for(lambda: (
            (e := dev_agent.server.state.eval_by_id(eval_id)) is not None
            and e.Status == EvalStatusComplete), timeout=30)
        assert wait_for(lambda: any(
            al.ClientStatus == "running"
            for al in dev_agent.server.state.allocs_by_job(job.ID)),
            timeout=30)
        alloc = dev_agent.server.state.allocs_by_job(job.ID)[0]

        rc, out, err = run_cli(capsys, "fs", "-address", address,
                               alloc.ID[:8], "alloc/logs")
        assert rc == 0 and "echoer" in out, (out, err)

        log = next(l.split()[-1] for l in out.splitlines()
                   if "stdout" in l)
        assert wait_for(lambda: run_cli(
            capsys, "fs", "-address", address, "-cat", alloc.ID,
            f"alloc/logs/{log}")[1].find("fs-cli-test") >= 0, timeout=20)

        rc, out, _ = run_cli(capsys, "fs", "-address", address, "-stat",
                             alloc.ID, f"alloc/logs/{log}")
        assert rc == 0 and log in out

    def test_monitor_follows_eval(self, capsys, address, dev_agent):
        from nomad_tpu import mock

        job = mock.job()
        job.ID = job.Name = "monitor-job"
        tg = job.TaskGroups[0]
        tg.Count = 1
        task = tg.Tasks[0]
        task.Driver = "mock_driver"
        task.Config = {"run_for": 30}
        task.Resources.Networks = []
        task.Services = []
        eval_id, _, _ = dev_agent.server.job_register(job)
        rc, out, _ = run_cli(capsys, "monitor", "-address", address,
                             eval_id)
        assert rc == 0
        assert "complete" in out

    def test_plan_shows_diff_for_new_job(self, capsys, address, dev_agent,
                                         jobfile):
        path, text = jobfile
        import shutil
        import tempfile

        # A renamed copy is guaranteed-new: plan must render a CREATE diff
        # with added fields and the scheduler annotation summary.
        d = tempfile.mkdtemp()
        newpath = os.path.join(d, "planned.nomad")
        shutil.copy(path, newpath)
        new_text = text.replace('"example"', '"planned"')
        with open(newpath, "w") as f:
            f.write(new_text)
        rc, out, _ = run_cli(capsys, "plan", "-address", address, newpath)
        assert rc == 1  # changes would be made
        assert "+ Job" in out or "+ job" in out.lower()
        assert "create" in out.lower() or "+" in out
