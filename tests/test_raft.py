"""Raft consensus tests (reference shapes: nomad/leader_test.go:14-288 —
election, failover, singleton enable/disable across leadership;
nomad/fsm.go snapshot/restore; raft log persistence).

All clusters are in-process over the loopback InMemTransport with tightened
timeouts (reference: server_test.go:46-52 tightens Raft the same way).
"""

import threading

import msgpack
import pytest

from nomad_tpu import mock
from nomad_tpu.raft import (
    FileLogStore,
    InMemLogStore,
    InMemTransport,
    LogEntry,
    NotLeaderError,
    RaftConfig,
    RaftNode,
)
from nomad_tpu.raft.log import EntryType
from nomad_tpu.raft.transport import BoundTransport


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked cluster suite: one retry

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.06,
                  election_timeout_max=0.12, apply_timeout=5.0)


class AppendFSM:
    """Toy FSM: appends (index, decoded payload) pairs."""

    def __init__(self):
        self.applied = []
        self.lock = threading.Lock()

    def apply(self, index, etype, data):
        val = msgpack.unpackb(data, raw=False)
        with self.lock:
            self.applied.append((index, val))
        return val

    def snapshot(self):
        with self.lock:
            return msgpack.packb(self.applied, use_bin_type=True)

    def restore(self, blob):
        with self.lock:
            self.applied = [tuple(x) for x in msgpack.unpackb(blob, raw=False)]


def make_cluster(n, transport=None, configs=None, stores=None):
    transport = transport or InMemTransport()
    ids = [f"s{i}" for i in range(n)]
    nodes, fsms = [], []
    for i, nid in enumerate(ids):
        fsm = AppendFSM()
        node = RaftNode(
            node_id=nid, peers=list(ids),
            log_store=(stores[i] if stores else InMemLogStore()),
            transport=BoundTransport(transport, nid),
            apply_fn=fsm.apply, snapshot_fn=fsm.snapshot,
            restore_fn=fsm.restore,
            config=(configs[i] if configs else FAST))
        nodes.append(node)
        fsms.append(fsm)
    for node in nodes:
        node.start()
    return transport, nodes, fsms


def leader_of(nodes):
    leaders = [n for n in nodes if n.is_leader() and n.role == "leader"]
    return leaders[0] if len(leaders) == 1 else None


def cmd(value):
    return msgpack.packb(value, use_bin_type=True)


def shutdown_all(nodes):
    for n in nodes:
        n.shutdown()


class TestSingleNode:
    def test_self_elects_and_applies(self):
        _, nodes, fsms = make_cluster(1)
        try:
            assert wait_for(lambda: nodes[0].is_leader())
            index, result = nodes[0].apply_command(cmd({"op": 1}))
            assert result == {"op": 1}
            assert fsms[0].applied[-1] == (index, {"op": 1})
        finally:
            shutdown_all(nodes)

    def test_restart_recovers_from_file_log(self, tmp_path):
        store = FileLogStore(str(tmp_path / "raft"))
        transport = InMemTransport()
        _, nodes, fsms = make_cluster(1, transport=transport, stores=[store])
        try:
            assert wait_for(lambda: nodes[0].is_leader())
            for i in range(5):
                nodes[0].apply_command(cmd(i))
            applied = list(fsms[0].applied)
        finally:
            shutdown_all(nodes)
        store.close()

        store2 = FileLogStore(str(tmp_path / "raft"))
        _, nodes2, fsms2 = make_cluster(1, stores=[store2])
        try:
            assert wait_for(lambda: nodes2[0].is_leader())
            # Replay happens via commit advancement after the noop barrier.
            assert wait_for(
                lambda: [v for _, v in fsms2[0].applied] == [v for _, v in
                                                             applied])
        finally:
            shutdown_all(nodes2)


class TestElection:
    def test_three_node_single_leader(self):
        _, nodes, _ = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            followers = [n for n in nodes if n is not leader]
            assert all(n.role == "follower" for n in followers)
            # Followers learn the leader id through heartbeats.
            assert wait_for(lambda: all(
                n.leader_id == leader.id for n in followers))
        finally:
            shutdown_all(nodes)

    def test_leader_loss_triggers_failover(self):
        """(reference: nomad/leader_test.go:14-139 leader loss/rejoin)"""
        transport, nodes, _ = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            transport.take_down(leader.id)
            rest = [n for n in nodes if n is not leader]
            assert wait_for(lambda: any(n.is_leader() for n in rest))
            # Old leader rejoins as follower once it sees the higher term.
            transport.bring_up(leader.id)
            assert wait_for(lambda: leader_of(nodes) is not None)
            new_leader = leader_of(nodes)
            assert wait_for(
                lambda: leader.role == "follower" or leader is new_leader)
        finally:
            shutdown_all(nodes)

    def test_partitioned_candidate_rejoin(self):
        transport, nodes, fsms = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            isolated = [n for n in nodes if n is not leader][0]
            for other in nodes:
                if other is not isolated:
                    transport.partition(isolated.id, other.id)
            # Majority side keeps working.
            index, _ = leader.apply_command(cmd("during-partition"))
            transport.heal()
            # Isolated node converges to the committed log.
            fsm = fsms[nodes.index(isolated)]
            assert wait_for(lambda: any(
                v == "during-partition" for _, v in fsm.applied))
        finally:
            shutdown_all(nodes)


class TestReplication:
    def test_commands_replicate_to_all(self):
        _, nodes, fsms = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            for i in range(10):
                leader.apply_command(cmd(i))
            for fsm in fsms:
                assert wait_for(
                    lambda f=fsm: [v for _, v in f.applied] == list(range(10)))
        finally:
            shutdown_all(nodes)

    def test_apply_on_follower_raises_not_leader(self):
        _, nodes, _ = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            follower = [n for n in nodes if n is not leader][0]
            # The hint arrives with the first AppendEntries from the new
            # leader; wait for it so the assertion isn't heartbeat-raced.
            assert wait_for(lambda: follower.leader_id == leader.id)
            with pytest.raises(NotLeaderError) as exc:
                follower.apply_command(cmd("nope"))
            assert exc.value.leader_hint == leader.id
        finally:
            shutdown_all(nodes)

    def test_lagging_follower_catches_up(self):
        transport, nodes, fsms = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            lag = [n for n in nodes if n is not leader][0]
            transport.take_down(lag.id)
            for i in range(20):
                leader.apply_command(cmd(i))
            transport.bring_up(lag.id)
            fsm = fsms[nodes.index(lag)]
            assert wait_for(
                lambda: [v for _, v in fsm.applied] == list(range(20)))
        finally:
            shutdown_all(nodes)

    def test_barrier_commits_prior_terms(self):
        _, nodes, _ = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            index = leader.barrier()
            assert index >= 1
            assert leader.applied_index >= 0
            assert leader.commit_index >= index
        finally:
            shutdown_all(nodes)


class TestSnapshot:
    def test_snapshot_truncates_and_restores_lagger(self):
        """A follower that falls behind a compacted log gets an
        InstallSnapshot (reference role: raft snapshot + restore path,
        fsm.go:430-551)."""
        cfgs = [RaftConfig(heartbeat_interval=0.02,
                           election_timeout_min=0.06,
                           election_timeout_max=0.12,
                           snapshot_threshold=10, trailing_logs=2)
                for _ in range(3)]
        transport, nodes, fsms = make_cluster(3, configs=cfgs)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            lag = [n for n in nodes if n is not leader][0]
            transport.take_down(lag.id)
            for i in range(30):
                leader.apply_command(cmd(i))
            leader.take_snapshot()
            assert leader.log.first_index() > 1
            transport.bring_up(lag.id)
            fsm = fsms[nodes.index(lag)]
            assert wait_for(
                lambda: [v for _, v in fsm.applied][-1:] == [29], timeout=15)
            # The restored follower state covers every command.
            vals = [v for _, v in fsm.applied]
            restored = fsms[nodes.index(lag)]
            assert vals[-1] == 29
        finally:
            shutdown_all(nodes)


class TestMembership:
    def test_add_peer_replicates(self):
        transport, nodes, fsms = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            # Boot a fourth node configured with no peers; it joins via
            # config change (reference: Serf-driven AddPeer,
            # leader.go:421-447).
            fsm = AppendFSM()
            newbie = RaftNode(
                node_id="s3", peers=[n.id for n in nodes] + ["s3"],
                log_store=InMemLogStore(),
                transport=BoundTransport(transport, "s3"),
                apply_fn=fsm.apply, snapshot_fn=fsm.snapshot,
                restore_fn=fsm.restore, config=FAST)
            newbie.start()
            nodes.append(newbie)
            fsms.append(fsm)
            leader.add_peer("s3")
            leader.apply_command(cmd("after-join"))
            assert wait_for(lambda: any(
                v == "after-join" for _, v in fsm.applied))
            assert "s3" in leader.peers()
        finally:
            shutdown_all(nodes)

    def test_remove_peer(self):
        _, nodes, _ = make_cluster(3)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            victim = [n for n in nodes if n is not leader][0]
            leader.remove_peer(victim.id)
            assert wait_for(lambda: victim.id not in leader.peers())
            # Two-node majority still commits.
            leader.apply_command(cmd("post-remove"))
        finally:
            shutdown_all(nodes)


class TestFileLogStore:
    def test_roundtrip(self, tmp_path):
        store = FileLogStore(str(tmp_path))
        entries = [LogEntry(Index=i, Term=1, Type=EntryType.Command,
                            Data=msgpack.packb(i)) for i in range(1, 11)]
        store.store_entries(entries)
        store.set_stable("term", 7)
        store.store_snapshot(5, 1, b"snapdata")
        store.close()

        st2 = FileLogStore(str(tmp_path))
        assert st2.first_index() == 1
        assert st2.last_index() == 10
        assert st2.get_entry(4).Data == msgpack.packb(4)
        assert st2.get_stable("term") == 7
        assert st2.latest_snapshot() == (5, 1, b"snapdata")
        st2.delete_range(1, 5)
        st2.close()

        st3 = FileLogStore(str(tmp_path))
        assert st3.first_index() == 6
        assert st3.get_entry(3) is None
        st3.close()

    def test_torn_tail_write_dropped(self, tmp_path):
        store = FileLogStore(str(tmp_path))
        store.store_entries([LogEntry(Index=1, Term=1, Data=b"ok")])
        store.close()
        with open(str(tmp_path / "raft.log"), "ab") as fh:
            fh.write(b"\xff\xff\xff\x7f partial")
        st2 = FileLogStore(str(tmp_path))
        assert st2.last_index() == 1
        assert st2.get_entry(1).Data == b"ok"
        st2.close()


class TestLogStoreCRC:
    def test_corrupt_middle_record_truncates_from_there(self, tmp_path):
        """A bit-flip in the middle of the segment must not feed garbage
        into raft replay: the CRC stops the scan and the valid prefix
        survives."""
        store = FileLogStore(str(tmp_path))
        store.store_entries([LogEntry(Index=i, Term=1, Data=b"x" * 20)
                             for i in range(1, 6)])
        store.close()
        path = str(tmp_path / "raft.log")
        raw = bytearray(open(path, "rb").read())
        # Flip a byte inside the third record's payload.
        raw[len(raw) // 2] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        st2 = FileLogStore(str(tmp_path))
        assert 1 <= st2.last_index() < 5
        for i in range(1, st2.last_index() + 1):
            assert st2.get_entry(i).Data == b"x" * 20
        st2.close()

    def test_legacy_headerless_segment_upgrades(self, tmp_path):
        """Pre-CRC segment files (no magic) replay and are rewritten in the
        v2 format on open."""
        import struct as _struct

        path = str(tmp_path / "raft.log")
        with open(path, "wb") as fh:
            for i in range(1, 4):
                rec = LogEntry(Index=i, Term=1, Data=b"old").pack()
                fh.write(_struct.pack("<I", len(rec)) + rec)
        store = FileLogStore(str(tmp_path))
        assert store.last_index() == 3
        store.close()
        assert open(path, "rb").read(4) == b"NTL2"
        st2 = FileLogStore(str(tmp_path))
        assert st2.last_index() == 3
        st2.close()


class TestNativeLogStore:
    @pytest.fixture
    def native(self):
        from nomad_tpu.raft.native_log import NativeLogStore, load_liblogstore

        if load_liblogstore() is None:
            pytest.skip("liblogstore.so not built")
        return NativeLogStore

    def test_roundtrip_and_format_interop(self, native, tmp_path):
        """Entries written natively read back through BOTH backends — the
        on-disk format is shared, so nodes can switch freely."""
        store = native(str(tmp_path))
        entries = [LogEntry(Index=i, Term=2, Data=msgpack.packb(i * 7))
                   for i in range(1, 21)]
        store.store_entries(entries)
        store.set_stable("votedFor", "n1")
        store.store_snapshot(10, 2, b"snap")
        store.close()

        nat2 = native(str(tmp_path))
        assert nat2.last_index() == 20
        assert nat2.get_entry(13).Data == msgpack.packb(91)
        assert nat2.get_stable("votedFor") == "n1"
        assert nat2.latest_snapshot() == (10, 2, b"snap")
        nat2.close()

        py = FileLogStore(str(tmp_path))
        assert py.last_index() == 20
        assert py.get_entry(20).Term == 2
        py.close()

        # And the reverse: python writes, native reads.
        py = FileLogStore(str(tmp_path))
        py.store_entries([LogEntry(Index=21, Term=3, Data=b"py")])
        py.close()
        nat3 = native(str(tmp_path))
        assert nat3.last_index() == 21
        assert nat3.get_entry(21).Data == b"py"
        nat3.close()

    def test_native_compaction_and_truncation(self, native, tmp_path):
        store = native(str(tmp_path))
        store.store_entries([LogEntry(Index=i, Term=1, Data=b"d")
                             for i in range(1, 11)])
        store.delete_range(1, 6)  # snapshot compaction
        store.close()
        st2 = native(str(tmp_path))
        assert st2.first_index() == 7
        assert st2.last_index() == 10
        st2.delete_range(9, 10)  # conflict truncation
        st2.close()
        st3 = native(str(tmp_path))
        assert st3.last_index() == 8
        st3.close()

    def test_native_corrupt_tail_truncated(self, native, tmp_path):
        store = native(str(tmp_path))
        store.store_entries([LogEntry(Index=1, Term=1, Data=b"keep")])
        store.close()
        path = str(tmp_path / "raft.log")
        with open(path, "ab") as fh:
            fh.write(b"\x10\x00\x00\x00\xde\xad\xbe\xefgarbagegarbage!!")
        st2 = native(str(tmp_path))
        assert st2.last_index() == 1
        assert st2.get_entry(1).Data == b"keep"
        st2.close()

    def test_native_cluster_replicates(self, native, tmp_path):
        """A real networked server on the native log store: elects, commits
        a job, restarts from the native segment."""
        from nomad_tpu.rpc.cluster import ClusterServer
        from nomad_tpu.server.server import ServerConfig
        from nomad_tpu import mock
        from nomad_tpu.structs import to_dict
        from helpers import wait_for

        cs = ClusterServer(ServerConfig(num_schedulers=0))
        cs.connect([cs.addr], log_store=native(str(tmp_path)),
                   raft_config=RaftConfig(
                       heartbeat_interval=0.02, election_timeout_min=0.08,
                       election_timeout_max=0.16, apply_timeout=5.0))
        cs.start()
        try:
            assert wait_for(lambda: cs.server.is_leader()
                            and cs.server._leader, timeout=20)
            job = mock.job()
            cs.endpoints.handle("Job.Register", {"Job": to_dict(job)})
            assert cs.server.state.job_by_id(job.ID) is not None
        finally:
            cs.shutdown()
        # The segment survived with entries.
        st = native(str(tmp_path))
        assert st.last_index() > 0
        st.close()


class StreamFSM(AppendFSM):
    """AppendFSM with the streaming-snapshot seam: state streams as
    bounded chunks (4 entries each), restore stages and cuts over only
    when the whole stream arrived."""

    CHUNK = 4

    def snapshot_chunks(self):
        with self.lock:
            items = list(self.applied)

        def gen():
            for i in range(0, len(items), self.CHUNK):
                yield items[i:i + self.CHUNK]
        return gen()

    def restore_chunks(self, chunks):
        staged = []
        for c in chunks:
            staged.extend(tuple(x) for x in c)
        with self.lock:
            self.applied = staged


def make_stream_cluster(n, transport=None, configs=None, stores=None):
    """make_cluster, but every node runs the STREAMING snapshot path
    (chunked persist thread, chunked InstallSnapshot, staged restore)."""
    transport = transport or InMemTransport()
    ids = [f"s{i}" for i in range(n)]
    nodes, fsms = [], []
    for i, nid in enumerate(ids):
        fsm = StreamFSM()

        def restore_stream(raws, fsm=fsm):
            fsm.restore_chunks(
                msgpack.unpackb(b, raw=False) for b in raws)

        node = RaftNode(
            node_id=nid, peers=list(ids),
            log_store=(stores[i] if stores else InMemLogStore()),
            transport=BoundTransport(transport, nid),
            apply_fn=fsm.apply, snapshot_fn=fsm.snapshot,
            restore_fn=fsm.restore,
            snapshot_stream_fn=fsm.snapshot_chunks,
            restore_stream_fn=restore_stream,
            config=(configs[i] if configs else FAST))
        nodes.append(node)
        fsms.append(fsm)
    for node in nodes:
        node.start()
    return transport, nodes, fsms


class TestStreamingSnapshot:
    """ISSUE 13 tentpole: chunked snapshot persist/restore + chunked
    InstallSnapshot, with the `raft.snapshot.chunk` /
    `raft.install_snapshot` failpoints proving a torn stream can never
    tear state."""

    @pytest.fixture(autouse=True)
    def _heal(self):
        from nomad_tpu.resilience import failpoints
        failpoints.disarm_all()
        yield
        failpoints.disarm_all()

    def test_streaming_persist_restart_recovers(self, tmp_path):
        """A chunked snapshot lands on disk in the NTS1 framed format and
        a restart restores from it chunk-by-chunk."""
        store = FileLogStore(str(tmp_path / "raft"))
        _, nodes, fsms = make_stream_cluster(1, stores=[store])
        try:
            assert wait_for(lambda: nodes[0].is_leader())
            for i in range(30):
                nodes[0].apply_command(cmd(i))
            snap_index = nodes[0].take_snapshot()
            assert snap_index > 0
            chunked = store.latest_snapshot_chunks()
            assert chunked is not None and chunked[0] == snap_index
            # Meta chunk + ceil(30/4) data chunks: genuinely streamed.
            assert len(chunked[2]) >= 8
            applied = [v for _, v in fsms[0].applied]
        finally:
            shutdown_all(nodes)
        store.close()

        with open(str(tmp_path / "raft" / "snapshot.mp"), "rb") as fh:
            assert fh.read(4) == b"NTS1"
        store2 = FileLogStore(str(tmp_path / "raft"))
        _, nodes2, fsms2 = make_stream_cluster(1, stores=[store2])
        try:
            assert wait_for(lambda: nodes2[0].is_leader())
            assert wait_for(
                lambda: [v for _, v in fsms2[0].applied] == applied)
        finally:
            shutdown_all(nodes2)
        store2.close()

    def test_torn_chunk_stream_keeps_previous_snapshot(self, tmp_path):
        """`raft.snapshot.chunk` drop = torn persist stream: the persist
        aborts wholesale, the PREVIOUS snapshot stays intact on disk and
        in memory, the log is NOT truncated, and the re-armed threshold
        retries once healed."""
        from nomad_tpu.resilience import failpoints

        store = FileLogStore(str(tmp_path / "raft"))
        _, nodes, fsms = make_stream_cluster(1, stores=[store])
        try:
            assert wait_for(lambda: nodes[0].is_leader())
            for i in range(10):
                nodes[0].apply_command(cmd(i))
            first_snap = nodes[0].take_snapshot()
            assert first_snap > 0
            before = store.latest_snapshot_chunks()
            with open(str(tmp_path / "raft" / "snapshot.mp"), "rb") as fh:
                disk_before = fh.read()

            for i in range(10, 20):
                nodes[0].apply_command(cmd(i))
            fired_before = failpoints.snapshot().get(
                "raft.snapshot.chunk", {}).get("fired", 0)
            failpoints.arm_from_spec("raft.snapshot.chunk=drop:count=1")
            first_idx = nodes[0].log.first_index()
            torn = nodes[0].take_snapshot()
            # The persist aborted: snapshot index unmoved, prior chunked
            # snapshot intact in memory AND on disk, log kept.
            assert torn == first_snap
            assert store.latest_snapshot_chunks() == before
            with open(str(tmp_path / "raft" / "snapshot.mp"), "rb") as fh:
                assert fh.read() == disk_before
            assert nodes[0].log.first_index() == first_idx
            assert failpoints.snapshot()["raft.snapshot.chunk"][
                "fired"] - fired_before == 1

            # Healed (count=1 self-disarmed): the next persist lands.
            healed = nodes[0].take_snapshot()
            assert healed > first_snap
            assert store.latest_snapshot_chunks()[0] == healed
        finally:
            shutdown_all(nodes)
        store.close()

    def test_snapshot_file_corruption_discarded_not_restored(self,
                                                             tmp_path):
        """Bit rot in a published chunked snapshot file fails the CRC and
        the whole snapshot is DISCARDED at load — boot falls back to log
        replay rather than restoring garbage."""
        import os
        path = str(tmp_path / "raft")
        store = FileLogStore(path)
        snap_file = os.path.join(path, "snapshot.mp")
        store.store_snapshot_chunks(
            5, 1, [msgpack.packb((5, 1)), b"chunk-a", b"chunk-b"])
        assert store.latest_snapshot_chunks() is not None
        store.close()
        with open(snap_file, "r+b") as fh:
            fh.seek(-2, 2)
            fh.write(b"\xff")
        store2 = FileLogStore(path)
        assert store2.latest_snapshot_chunks() is None
        assert store2.latest_snapshot() is None
        store2.close()

    def test_chunked_install_snapshot_catches_up_lagger(self):
        """A follower behind a compacted log catches up through the
        SEQUENCE of bounded InstallSnapshot RPCs — including surviving a
        dropped chunk hop (`raft.install_snapshot`), which must restart
        the stream rather than install a partial snapshot."""
        from nomad_tpu.resilience import failpoints

        cfgs = [RaftConfig(heartbeat_interval=0.02,
                           election_timeout_min=0.06,
                           election_timeout_max=0.12,
                           snapshot_threshold=10, trailing_logs=2)
                for _ in range(3)]
        transport, nodes, fsms = make_stream_cluster(3, configs=cfgs)
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            lag = [n for n in nodes if n is not leader][0]
            transport.take_down(lag.id)
            for i in range(30):
                leader.apply_command(cmd(i))
            leader.take_snapshot()
            assert leader.log.first_index() > 1
            assert leader.log.latest_snapshot_chunks() is not None
            # One chunk hop of the install stream is black-holed: the
            # follower's staged stream must go stale and restart, never
            # install partially.
            failpoints.arm_from_spec("raft.install_snapshot=drop:count=1")
            transport.bring_up(lag.id)
            fsm = fsms[nodes.index(lag)]
            assert wait_for(
                lambda: [v for _, v in fsm.applied][-1:] == [29],
                timeout=15)
            assert failpoints.snapshot()[
                "raft.install_snapshot"]["fired"] >= 1
            # Exactly the stream's content, in order, nothing doubled.
            vals = [v for _, v in fsm.applied]
            assert vals == sorted(set(vals))
        finally:
            shutdown_all(nodes)
