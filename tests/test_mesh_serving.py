"""Multi-chip SERVING: the pipelined worker's windows run on a sharded mesh.

The node tensor (and every placement-kernel input) shards its node axis over
a jax.sharding.Mesh; XLA's SPMD partitioner turns the same place_batch
program into the multi-chip version. These tests run on the 8-virtual-CPU
mesh from conftest and assert the mesh-served path is indistinguishable from
single-device serving (reference frame: SURVEY §7.1 — the node axis IS the
sharded tensor axis; the serving semantics come from nomad/worker.go +
plan_apply.go, which don't care where the argmax ran).
"""

import random

import numpy as np
import pytest

import jax

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete
from nomad_tpu.tensor.node_table import alloc_vec

from helpers import wait_for  # noqa: E402


def _fixed_noise(n_rows, rng):
    """Deterministic tie-break noise so two servers place identically."""
    return np.asarray(
        np.random.default_rng(1234).random(n_rows), dtype=np.float32) * 1e-3


def _make_server(mesh: bool, window: int = 16) -> Server:
    cfg = ServerConfig(num_schedulers=1, pipelined_scheduling=True,
                       scheduler_window=window,
                       scheduler_mesh="all" if mesh else "",
                       min_heartbeat_ttl=3600.0, heartbeat_grace=3600.0)
    srv = Server(cfg)
    srv.establish_leadership()
    return srv


def _nodes(n, seed=7):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        node = mock.node()
        node.Meta["rack"] = f"r{i % 8}"
        node.Resources.CPU = 2000 + 400 * (i % 3)
        node.Resources.MemoryMB = 4096
        from nomad_tpu.structs import compute_node_class

        compute_node_class(node)
        out.append(node)
    return out


def _job(count=6):
    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = count
    task = tg.Tasks[0]
    task.Resources.CPU = 50
    task.Resources.MemoryMB = 64
    task.Resources.Networks = []
    task.Services = []
    return job


def _run_stream(srv, jobs):
    """Submit jobs one at a time (deterministic eval order and window fill),
    wait for each eval, return placements as job -> sorted node IDs."""
    placements = {}
    for job in jobs:
        eval_id = srv.job_register(job)[0]
        wait_for(lambda: (e := srv.state.eval_by_id(eval_id)) is not None
                 and e.Status == EvalStatusComplete, timeout=60)
        placements[job.ID] = sorted(
            a.NodeID for a in srv.state.allocs_by_job(job.ID)
            if not a.terminal_status())
    return placements


class TestMeshServing:
    def test_mesh_is_wired_into_the_served_tensor(self):
        srv = _make_server(mesh=True)
        try:
            assert srv.tindex.nt.mesh is not None
            assert srv.tindex.nt.mesh.devices.size == len(jax.devices())
            for node in _nodes(8):
                srv.node_register(node)
            arrays = srv.tindex.nt.device_arrays()
            # The served table's arrays are actually sharded over the mesh.
            sh = arrays["usage"].sharding
            assert getattr(sh, "mesh", None) is not None
            assert sh.spec[0] is not None, "node axis not sharded"
        finally:
            srv.shutdown()

    def test_sharded_serving_places_identically(self, monkeypatch):
        """Same node set, same job stream, same tie-break noise: the mesh
        server and the single-device server commit identical placements."""
        from nomad_tpu.scheduler import stack as stack_mod

        monkeypatch.setattr(stack_mod, "make_noise_vec", _fixed_noise)

        import pickle

        nodes = _nodes(32)
        jobs = [_job() for _ in range(6)]
        results = []
        for mesh in (False, True):
            srv = _make_server(mesh=mesh)
            try:
                for node in pickle.loads(pickle.dumps(nodes)):
                    srv.node_register(node)
                placements = _run_stream(
                    srv, pickle.loads(pickle.dumps(jobs)))
                results.append(placements)
            finally:
                srv.shutdown()
        single, sharded = results
        assert single == sharded

    def test_mesh_burst_places_all_without_oversubscription(self):
        """A windowed burst through the mesh-served path: every eval
        completes, every placement commits, and no node oversubscribes."""
        srv = _make_server(mesh=True, window=8)
        try:
            nodes = _nodes(16)
            for node in nodes:
                srv.node_register(node)
            eval_ids = [srv.job_register(_job(count=4))[0]
                        for _ in range(12)]
            wait_for(lambda: all(
                (e := srv.state.eval_by_id(eid)) is not None
                and e.Status == EvalStatusComplete for eid in eval_ids),
                timeout=120)
            total = 0
            for eid in eval_ids:
                allocs = list(srv.state.allocs_by_eval(eid))
                total += len(allocs)
            assert total == 12 * 4
            for node in nodes:
                used = sum(alloc_vec(a)[0]
                           for a in srv.state.allocs_by_node(node.ID)
                           if not a.terminal_status())
                assert used <= node.Resources.CPU
        finally:
            srv.shutdown()
