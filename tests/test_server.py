"""Server services tests (shaped after reference nomad/eval_broker_test.go,
blocked_evals_test.go, plan_apply_test.go, leader_test.go scenarios)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import (
    BlockedEvals,
    DevRaft,
    EvalBroker,
    FSM,
    MessageType,
    PlanQueue,
    Server,
    ServerConfig,
    TimeTable,
    evaluate_plan,
)
from nomad_tpu.server.eval_broker import FAILED_QUEUE, TokenMismatchError
from nomad_tpu.structs import Plan
from nomad_tpu.structs.structs import (
    AllocClientStatusComplete,
    EvalStatusBlocked,
    EvalStatusComplete,
    EvalStatusPending,
    NodeStatusDown,
    NodeStatusReady,
)


from helpers import wait_for  # noqa: E402

class TestEvalBroker:
    def _broker(self, **kw):
        b = EvalBroker(**{"nack_timeout": 5.0, "delivery_limit": 3, **kw})
        b.set_enabled(True)
        return b

    def test_enqueue_dequeue_ack(self):
        b = self._broker()
        ev = mock.eval()
        b.enqueue(ev)
        out, token = b.dequeue(["service"], timeout=1)
        assert out.ID == ev.ID
        assert b.outstanding(ev.ID) == token
        b.ack(ev.ID, token)
        assert b.outstanding(ev.ID) is None
        out2, _ = b.dequeue(["service"], timeout=0.1)
        assert out2 is None

    def test_priority_order(self):
        b = self._broker()
        low, high = mock.eval(), mock.eval()
        low.Priority = 10
        high.Priority = 90
        b.enqueue(low)
        b.enqueue(high)
        first, t1 = b.dequeue(["service"], timeout=1)
        assert first.ID == high.ID

    def test_scheduler_type_routing(self):
        b = self._broker()
        ev = mock.eval()
        ev.Type = "batch"
        b.enqueue(ev)
        none, _ = b.dequeue(["system"], timeout=0.1)
        assert none is None
        got, _ = b.dequeue(["batch", "system"], timeout=1)
        assert got.ID == ev.ID

    def test_job_serialization(self):
        """Two evals for one job: second waits until first is acked."""
        b = self._broker()
        e1, e2 = mock.eval(), mock.eval()
        e2.JobID = e1.JobID
        b.enqueue(e1)
        b.enqueue(e2)
        got1, t1 = b.dequeue(["service"], timeout=1)
        none, _ = b.dequeue(["service"], timeout=0.1)
        assert none is None, "second eval for same job must be held"
        b.ack(got1.ID, t1)
        got2, t2 = b.dequeue(["service"], timeout=1)
        assert got2.ID == e2.ID
        b.ack(got2.ID, t2)

    def test_nack_requeues(self):
        b = self._broker()
        ev = mock.eval()
        b.enqueue(ev)
        got, token = b.dequeue(["service"], timeout=1)
        b.nack(got.ID, token)
        got2, token2 = b.dequeue(["service"], timeout=1)
        assert got2.ID == ev.ID
        assert token2 != token

    def test_nack_timeout_redelivery(self):
        b = self._broker(nack_timeout=0.1)
        ev = mock.eval()
        b.enqueue(ev)
        got, token = b.dequeue(["service"], timeout=1)
        # Don't ack; wait for auto-nack.
        got2, token2 = b.dequeue(["service"], timeout=2)
        assert got2.ID == ev.ID
        with pytest.raises(TokenMismatchError):
            b.ack(ev.ID, token)  # stale token rejected
        b.ack(ev.ID, token2)

    def test_delivery_limit_failed_queue(self):
        b = self._broker(delivery_limit=2)
        ev = mock.eval()
        b.enqueue(ev)
        for _ in range(2):
            got, token = b.dequeue(["service"], timeout=1)
            b.nack(got.ID, token)
        got, token = b.dequeue([FAILED_QUEUE], timeout=1)
        assert got.ID == ev.ID

    def test_wait_time_deferral(self):
        b = self._broker()
        ev = mock.eval()
        ev.Wait = int(0.2 * 1e9)
        b.enqueue(ev)
        none, _ = b.dequeue(["service"], timeout=0.05)
        assert none is None
        got, _ = b.dequeue(["service"], timeout=2)
        assert got.ID == ev.ID

    def test_disabled_drops(self):
        b = EvalBroker(5.0, 3)
        b.enqueue(mock.eval())
        b.set_enabled(True)
        none, _ = b.dequeue(["service"], timeout=0.05)
        assert none is None


class TestBlockedEvals:
    @staticmethod
    def _setup():
        broker = EvalBroker(5.0, 3)
        broker.set_enabled(True)
        blocked = BlockedEvals(broker)
        blocked.set_enabled(True)
        return broker, blocked

    def test_block_and_unblock_by_class(self):
        broker, blocked = self._setup()
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.ClassEligibility = {"v1:123": True}
        ev.SnapshotIndex = 10
        blocked.block(ev)
        assert blocked.stats.TotalBlocked == 1
        blocked.unblock("v1:123", 20)
        assert wait_for(lambda: broker.dequeue(["service"], timeout=0.1)[0] is not None)

    def test_ineligible_class_not_unblocked(self):
        broker, blocked = self._setup()
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.ClassEligibility = {"v1:bad": False}
        ev.SnapshotIndex = 10
        blocked.block(ev)
        blocked.unblock("v1:bad", 20)
        time.sleep(0.3)
        got, _ = broker.dequeue(["service"], timeout=0.05)
        assert got is None
        assert blocked.stats.TotalBlocked == 1

    def test_unknown_class_unblocks(self):
        """A class the eval never saw must unblock it (correctness rule)."""
        broker, blocked = self._setup()
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.ClassEligibility = {"v1:other": False}
        ev.SnapshotIndex = 10
        blocked.block(ev)
        blocked.unblock("v1:new-class", 20)
        assert wait_for(lambda: blocked.stats.TotalBlocked == 0)

    def test_escaped_always_unblocked(self):
        broker, blocked = self._setup()
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.EscapedComputedClass = True
        ev.SnapshotIndex = 10
        blocked.block(ev)
        assert blocked.stats.TotalEscaped == 1
        blocked.unblock("v1:anything", 20)
        assert wait_for(lambda: blocked.stats.TotalBlocked == 0)

    def test_missed_unblock(self):
        """Eval whose snapshot predates an unblock enqueues immediately."""
        broker, blocked = self._setup()
        blocked.unblock("v1:123", 100)
        time.sleep(0.1)
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.ClassEligibility = {"v1:123": True}
        ev.SnapshotIndex = 50  # older than unblock index 100
        blocked.block(ev)
        got, _ = broker.dequeue(["service"], timeout=1)
        assert got is not None and got.ID == ev.ID

    def test_duplicates(self):
        broker, blocked = self._setup()
        e1, e2 = mock.eval(), mock.eval()
        e2.JobID = e1.JobID
        for e in (e1, e2):
            e.Status = EvalStatusBlocked
        blocked.block(e1)
        blocked.block(e2)
        dups = blocked.get_duplicates(0.5)
        assert [d.ID for d in dups] == [e2.ID]


class TestPlanApply:
    def test_evaluate_plan_partial_commit(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = mock.node()
        raft.apply(MessageType.NodeRegister, {"Node": node})
        # Fill the node almost completely.
        big = mock.alloc()
        big.NodeID = node.ID
        big.Resources.CPU = 3800
        big.TaskResources = {}
        raft.apply(MessageType.AllocUpdate, {"Alloc": [big], "Job": big.Job})

        plan = Plan(EvalID="e1", Priority=50)
        ok_alloc = mock.alloc()
        ok_alloc.NodeID = node.ID
        ok_alloc.Resources.CPU = 50
        ok_alloc.TaskResources = {}
        plan.NodeAllocation[node.ID] = [ok_alloc]
        ghost = mock.alloc()
        ghost.NodeID = "missing-node"
        plan.NodeAllocation["missing-node"] = [ghost]

        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert node.ID in result.NodeAllocation
        assert "missing-node" not in result.NodeAllocation
        assert result.RefreshIndex > 0  # partial commit

    def test_evaluate_plan_all_at_once_fails_whole(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = mock.node()
        raft.apply(MessageType.NodeRegister, {"Node": node})
        plan = Plan(EvalID="e1", Priority=50, AllAtOnce=True)
        ok_alloc = mock.alloc()
        ok_alloc.NodeID = node.ID
        plan.NodeAllocation[node.ID] = [ok_alloc]
        plan.NodeAllocation["missing"] = [mock.alloc()]
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert result.NodeAllocation == {}

    def test_overcommit_rejected(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = mock.node()
        raft.apply(MessageType.NodeRegister, {"Node": node})
        plan = Plan(EvalID="e1", Priority=50)
        huge = mock.alloc()
        huge.NodeID = node.ID
        huge.Resources.CPU = 100000
        huge.TaskResources = {}
        plan.NodeAllocation[node.ID] = [huge]
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert result.NodeAllocation == {}
        assert result.RefreshIndex > 0


class TestTimeTable:
    def test_witness_and_lookup(self):
        tt = TimeTable(granularity=1.0)
        tt.witness(100, 1000.0)
        tt.witness(200, 2000.0)
        assert tt.nearest_index(1500.0) == 100
        assert tt.nearest_index(2500.0) == 200
        assert tt.nearest_index(500.0) == 0

    def test_fsm_apply_witnesses_timetable(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        raft.apply(MessageType.NodeRegister, {"Node": mock.node()})
        assert fsm.timetable.nearest_index(time.time() + 1) > 0

    def test_timetable_survives_snapshot_restore(self):
        """GC thresholds depend on the index<->time map; after a failover
        restore the new leader must still translate times to indexes
        (reference: fsm.go:430-551 persists the timetable)."""
        now = time.time()
        fsm = FSM()
        fsm.timetable.witness(100, now - 2000.0)
        fsm.timetable.witness(200, now - 1000.0)
        raft = DevRaft(fsm)
        raft.apply(MessageType.NodeRegister, {"Node": mock.node()})
        snap = fsm.snapshot()

        fsm2 = FSM()
        fsm2.restore(snap)
        assert fsm2.timetable.nearest_index(now - 1500.0) == 100
        assert fsm2.timetable.nearest_index(now - 500.0) == 200
        # And it round-trips through msgpack like the raft snapshot path.
        import msgpack
        blob = msgpack.packb(snap, use_bin_type=True)
        fsm3 = FSM()
        fsm3.restore(msgpack.unpackb(blob, raw=False))
        assert fsm3.timetable.nearest_index(now - 1500.0) == 100

    def test_granularity_dedupe(self):
        tt = TimeTable(granularity=10.0)
        tt.witness(1, 100.0)
        tt.witness(2, 101.0)  # within granularity: dropped
        assert tt.nearest_index(200.0) == 1


class TestServerIntegration:
    def _server(self, ttl: float = 60.0, grace: float = 30.0):
        srv = Server(ServerConfig(num_schedulers=2, min_heartbeat_ttl=ttl,
                                  heartbeat_grace=grace))
        srv.establish_leadership()
        return srv

    def test_full_pipeline(self):
        srv = self._server()
        try:
            for _ in range(3):
                srv.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: (
                (e := srv.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete))
            allocs = srv.state.allocs_by_job(job.ID)
            assert len(allocs) == 10
            assert srv.state.job_by_id(job.ID).Status == "running"
        finally:
            srv.shutdown()

    def test_blocked_then_capacity_arrives(self):
        srv = self._server()
        try:
            job = mock.job()
            job.TaskGroups[0].Count = 2
            eval_id, _, _ = srv.job_register(job)
            # No nodes: placement fails, blocked eval parks.
            assert wait_for(lambda: srv.blocked_evals.stats.TotalBlocked == 1)
            # Capacity arrives: node registration unblocks by class.
            srv.node_register(mock.node())
            assert wait_for(lambda: len([
                a for a in srv.state.allocs_by_job(job.ID)
                if not a.terminal_status()]) == 2, timeout=20)
        finally:
            srv.shutdown()

    def test_heartbeat_expiry_marks_down_and_reschedules(self):
        srv = self._server(ttl=0.3, grace=0.2)
        try:
            n1 = mock.node()
            srv.node_register(n1)
            srv.node_update_status(n1.ID, NodeStatusReady)
            job = mock.job()
            job.TaskGroups[0].Count = 2
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: len(srv.state.allocs_by_job(job.ID)) == 2)
            # Stop heartbeating n1; second node will take the migrations.
            n2 = mock.node()
            srv.node_register(n2)
            srv.node_update_status(n2.ID, NodeStatusReady)

            def n2_keepalive():
                try:
                    srv.node_heartbeat(n2.ID)
                except KeyError:
                    pass
                return srv.state.node_by_id(n1.ID).Status == NodeStatusDown

            assert wait_for(n2_keepalive, timeout=20, interval=0.2)

            # All running allocs end up on n2 (keep n2's heartbeat alive
            # while we wait).
            def migrated():
                try:
                    srv.node_heartbeat(n2.ID)
                except KeyError:
                    pass
                allocs = srv.state.allocs_by_job(job.ID)
                running = [a for a in allocs if not a.terminal_status()]
                return running and all(a.NodeID == n2.ID for a in running)

            assert wait_for(migrated, timeout=20, interval=0.2)
        finally:
            srv.shutdown()

    def test_enforce_index(self):
        srv = self._server()
        try:
            job = mock.job()
            _, jmi, _ = srv.job_register(job)
            with pytest.raises(ValueError, match="Enforcing job modify index"):
                srv.job_register(job.copy(), enforce_index=jmi + 5)
            srv.job_register(job.copy(), enforce_index=jmi)
        finally:
            srv.shutdown()

    def test_periodic_job_dispatch(self):
        srv = self._server()
        try:
            job = mock.job()
            job.Type = "batch"
            from nomad_tpu.structs import PeriodicConfig
            from nomad_tpu.structs.structs import PeriodicSpecTest
            nxt = time.time() + 0.5
            job.Periodic = PeriodicConfig(Enabled=True,
                                          SpecType=PeriodicSpecTest,
                                          Spec=f"{nxt}")
            srv.node_register(mock.node())
            eval_id, _, _ = srv.job_register(job)
            assert eval_id == ""  # periodic parents aren't evaluated directly
            assert wait_for(lambda: len(
                srv.state.jobs_by_id_prefix(job.ID + "/periodic-")) == 1,
                timeout=20)
            launch = srv.state.periodic_launch_by_id(job.ID)
            assert launch is not None
        finally:
            srv.shutdown()

    def test_force_gc(self):
        srv = self._server()
        try:
            node = mock.node()
            srv.node_register(node)
            srv.node_update_status(node.ID, NodeStatusDown)
            srv.force_gc()
            assert wait_for(
                lambda: srv.state.node_by_id(node.ID) is None, timeout=20)
        finally:
            srv.shutdown()


class TestAllocUpdateCoalescing:
    """Server-side batching of Node.UpdateAlloc (reference: batchFuture +
    batchUpdateInterval, node_endpoint.go:530-593): concurrent client RPCs
    within one window must share a single raft entry, and every caller must
    observe that entry's commit index."""

    def _place(self, srv, n_nodes=3):
        for _ in range(n_nodes):
            srv.node_register(mock.node())
        job = mock.job()
        eval_id, _, _ = srv.job_register(job)
        assert wait_for(lambda: (
            (e := srv.state.eval_by_id(eval_id)) is not None
            and e.Status == EvalStatusComplete))
        return srv.state.allocs_by_job(job.ID)

    def test_concurrent_updates_share_one_raft_entry(self):
        import threading

        srv = Server(ServerConfig(
            num_schedulers=1, alloc_update_batch_interval=0.05))
        srv.establish_leadership()
        real_apply = srv.raft.apply
        try:
            allocs = self._place(srv)
            assert len(allocs) == 10
            applies = []

            def counting_apply(msg_type, payload):
                if msg_type == MessageType.AllocClientUpdate:
                    applies.append(len(payload["Alloc"]))
                return real_apply(msg_type, payload)

            srv.raft.apply = counting_apply
            indexes = []
            errors = []

            def one_rpc(alloc):
                upd = mock.alloc()
                upd.ID = alloc.ID
                upd.NodeID = alloc.NodeID
                upd.JobID = alloc.JobID
                upd.ClientStatus = AllocClientStatusComplete
                try:
                    indexes.append(srv.node_update_allocs([upd]))
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=one_rpc, args=(a,))
                       for a in allocs]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors
            # 10 concurrent RPCs -> far fewer consensus entries (typically
            # 1-2 windows), carrying all 10 updates between them.
            assert len(indexes) == 10
            assert len(applies) <= 3, f"{len(applies)} raft applies"
            assert sum(applies) == 10
            # Every caller got a real commit index, and the state reflects
            # every update at (or before) the index it was handed.
            assert all(i > 0 for i in indexes)
            for a in srv.state.allocs_by_job(allocs[0].JobID):
                assert a.ClientStatus == AllocClientStatusComplete
        finally:
            srv.raft.apply = real_apply
            srv.shutdown()

    def test_batching_disabled_applies_per_rpc(self):
        srv = Server(ServerConfig(
            num_schedulers=1, alloc_update_batch_interval=0.0))
        srv.establish_leadership()
        try:
            allocs = self._place(srv)
            upd = mock.alloc()
            upd.ID = allocs[0].ID
            upd.NodeID = allocs[0].NodeID
            upd.JobID = allocs[0].JobID
            upd.ClientStatus = AllocClientStatusComplete
            idx = srv.node_update_allocs([upd])
            assert idx > 0
            assert (srv.state.alloc_by_id(allocs[0].ID).ClientStatus
                    == AllocClientStatusComplete)
        finally:
            srv.shutdown()


class TestEvalBrokerReferenceGrid:
    """The eval_broker_test.go cases the original suite didn't cover:
    FIFO within a priority, empty-dequeue timeout, blocked dequeue
    wake-up, nack-timeout reset, ack at the delivery limit, and the
    EnqueueAll requeue-then-ack/nack transitions."""

    def _broker(self, **kw):
        b = EvalBroker(**{"nack_timeout": 5.0, "delivery_limit": 3, **kw})
        b.set_enabled(True)
        return b

    def test_dequeue_empty_times_out(self):
        """(reference: TestEvalBroker_Dequeue_Empty_Timeout)"""
        b = self._broker()
        t0 = time.monotonic()
        out, _ = b.dequeue(["service"], timeout=0.15)
        dt = time.monotonic() - t0
        assert out is None
        assert 0.1 <= dt < 2.0

    def test_dequeue_fifo_within_priority(self):
        """(reference: TestEvalBroker_Dequeue_FIFO)"""
        b = self._broker()
        evs = []
        for _ in range(10):
            ev = mock.eval()
            ev.Priority = 50
            b.enqueue(ev)
            evs.append(ev)
        order = []
        for _ in range(10):
            out, token = b.dequeue(["service"], timeout=1)
            order.append(out.ID)
            b.ack(out.ID, token)
        assert order == [e.ID for e in evs]

    def test_blocked_dequeue_wakes_on_enqueue(self):
        """(reference: TestEvalBroker_Dequeue_Blocked)"""
        import threading as _threading

        b = self._broker()
        got = {}

        def waiter():
            got["out"], got["token"] = b.dequeue(["service"], timeout=5)

        t = _threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        ev = mock.eval()
        b.enqueue(ev)
        t.join(timeout=5)
        assert not t.is_alive()
        assert got["out"].ID == ev.ID

    def test_nack_timeout_reset_defers_redelivery(self):
        """(reference: TestEvalBroker_Nack_TimeoutReset): an
        outstanding_reset pushes the redelivery deadline out, so the
        eval is NOT redelivered one original-timeout after dequeue."""
        # Generous margins: the reset must land well before the original
        # deadline and the check well before the pushed-out one, or a
        # loaded CI box races the wheel timer.
        b = self._broker(nack_timeout=1.5)
        ev = mock.eval()
        b.enqueue(ev)
        out, token = b.dequeue(["service"], timeout=1)
        assert out.ID == ev.ID
        time.sleep(0.9)
        b.outstanding_reset(ev.ID, token)  # deadline moves to ~t+2.4
        # At t+1.7 (past the original deadline) it must still be ours.
        time.sleep(0.8)
        assert b.outstanding(ev.ID) == token
        # Eventually the pushed-out deadline fires and it redelivers.
        out2, token2 = b.dequeue(["service"], timeout=5)
        assert out2.ID == ev.ID
        assert token2 != token

    def test_ack_at_delivery_limit(self):
        """(reference: TestEvalBroker_AckAtDeliveryLimit): the LAST
        allowed delivery can still be acked normally — the limit only
        routes the next redelivery to the failed queue."""
        b = self._broker(nack_timeout=5.0, delivery_limit=3)
        ev = mock.eval()
        b.enqueue(ev)
        for _ in range(2):
            out, token = b.dequeue(["service"], timeout=1)
            b.nack(out.ID, token)
        out, token = b.dequeue(["service"], timeout=1)  # delivery 3 of 3
        assert out.ID == ev.ID
        b.ack(ev.ID, token)
        assert b.outstanding(ev.ID) is None
        none, _ = b.dequeue(["service"], timeout=0.1)
        assert none is None

    def test_enqueue_all_requeue_then_ack(self):
        """(reference: TestEvalBroker_EnqueueAll_Requeue_Ack): a token-
        gated requeue of an outstanding eval stays parked until the ack,
        then becomes ready under a fresh token."""
        b = self._broker()
        ev = mock.eval()
        b.enqueue(ev)
        out, token = b.dequeue(["service"], timeout=1)
        assert out.ID == ev.ID
        b.enqueue_all({ev.ID: (ev, token)})
        assert b.stats.TotalReady == 0
        assert b.stats.TotalUnacked == 1
        b.ack(ev.ID, token)
        assert b.stats.TotalReady == 1
        assert b.stats.TotalUnacked == 0
        out2, token2 = b.dequeue(["service"], timeout=1)
        assert out2.ID == ev.ID
        assert token2 != token

    def test_enqueue_all_requeue_then_nack_drops_requeue(self):
        """(reference: TestEvalBroker_EnqueueAll_Requeue_Nack): a nack
        of the outstanding token discards the parked requeue — the
        ordinary nack redelivery takes over instead."""
        b = self._broker()
        ev = mock.eval()
        b.enqueue(ev)
        out, token = b.dequeue(["service"], timeout=1)
        b.enqueue_all({ev.ID: (ev, token)})
        b.nack(ev.ID, token)
        assert b.stats.TotalReady == 1
        assert b.stats.TotalUnacked == 0
        # Exactly ONE ready copy: the nack redelivery, not nack + requeue.
        out2, token2 = b.dequeue(["service"], timeout=1)
        assert out2.ID == ev.ID
        b.ack(ev.ID, token2)
        none, _ = b.dequeue(["service"], timeout=0.1)
        assert none is None


class TestBlockedEvalsReferenceGrid:
    """The blocked_evals_test.go cases the suite lacked: disabled no-op,
    same-job dedup into duplicates, prior-unblock immediate release
    (seen/unseen/escaped SnapshotIndex variants), duplicate fetch with
    blocking timeout, reblock token flow through the broker, and
    unblock_failed."""

    def setup_method(self, method):
        self._pairs = []

    def teardown_method(self, method):
        # Stop every capacity-watcher thread the test started.
        for blocked, broker in self._pairs:
            blocked.set_enabled(False)
            broker.set_enabled(False)

    def _pair(self):
        # Same construction (and argument order) as
        # TestBlockedEvals._setup, tracked for teardown.
        broker, blocked = TestBlockedEvals._setup()
        self._pairs.append((blocked, broker))
        return blocked, broker

    def _eval(self, escaped=False, elig=None, snapshot=0):
        ev = mock.eval()
        ev.Status = EvalStatusBlocked
        ev.EscapedComputedClass = escaped
        ev.ClassEligibility = dict(elig or {})
        ev.SnapshotIndex = snapshot
        return ev

    def test_block_disabled_is_noop(self):
        """(reference: TestBlockedEvals_Block_Disabled)"""
        blocked, _ = self._pair()
        blocked.set_enabled(False)
        blocked.block(self._eval(escaped=True))
        assert blocked.stats.TotalBlocked == 0
        assert blocked.stats.TotalEscaped == 0

    def test_duplicate_wakes_blocking_fetch(self):
        """(reference: TestBlockedEvals_GetDuplicates' second half; the
        immediate-fetch half is already pinned by
        TestBlockedEvals.test_duplicates): a duplicate arriving later
        wakes a BLOCKING get_duplicates call."""
        import threading as _threading

        blocked, _ = self._pair()
        e = self._eval()
        blocked.block(e)
        e3 = self._eval()
        e3.JobID = e.JobID
        timer = _threading.Timer(0.2, blocked.block, args=(e3,))
        timer.start()
        dups = blocked.get_duplicates(2.0)
        assert [d.ID for d in dups] == [e3.ID]

    def test_prior_unblock_keeps_ineligible_blocked(self):
        """(reference: TestBlockedEvals_Block_PriorUnblocks): capacity
        events for classes the eval is INELIGIBLE for don't release it."""
        blocked, _ = self._pair()
        blocked.unblock("v1:123", 1000)
        blocked.unblock("v1:123", 1001)
        ev = self._eval(elig={"v1:123": False, "v1:456": False},
                        snapshot=999)
        blocked.block(ev)
        assert blocked.stats.TotalBlocked == 1

    def test_immediate_unblock_variants(self):
        """(reference: the three Block_ImmediateUnblock_* cases): an
        escaped eval older than any unblock, or an eval whose snapshot
        predates an unseen/eligible class event, releases straight to
        the broker instead of parking."""
        for kwargs, released in (
            (dict(escaped=True, snapshot=900), True),      # escaped + old
            (dict(elig={}, snapshot=900), True),           # unseen class
            (dict(elig={"v1:123": True}, snapshot=900), True),   # eligible
            (dict(elig={"v1:123": False}, snapshot=900), False),  # seen, inelig
            (dict(escaped=True, snapshot=1100), False),    # newer than event
        ):
            blocked, broker = self._pair()
            # Seed the unblock index DIRECTLY instead of calling
            # unblock(): a queued capacity event is processed async and
            # releases ALL escaped evals regardless of index, which
            # would race the stays-blocked variants on a loaded box.
            with blocked._lock:
                blocked._unblock_indexes["v1:123"] = 1000
            blocked.block(self._eval(**kwargs))
            if released:
                out, token = broker.dequeue(["service"], timeout=1)
                assert out is not None, kwargs
                assert blocked.stats.TotalBlocked == 0
            else:
                assert blocked.stats.TotalBlocked == 1, kwargs

    def test_reblock_token_flow(self):
        """(reference: TestBlockedEvals_Reblock): a reblocked eval's
        unblock parks behind its outstanding token; the ack promotes it
        to ready under the broker's requeue path."""
        blocked, broker = self._pair()
        ev = self._eval(elig={"v1:123": True}, snapshot=500)
        broker.enqueue(ev)
        out, token = broker.dequeue([ev.Type], timeout=1)
        assert out.ID == ev.ID
        blocked.reblock(ev, token)
        assert blocked.stats.TotalBlocked == 1
        blocked.unblock("v1:123", 1000)
        assert wait_for(lambda: blocked.stats.TotalBlocked == 0)
        # Parked until the ack...
        assert broker.stats.TotalReady == 0
        assert broker.stats.TotalUnacked == 1
        broker.ack(ev.ID, token)
        # ...then ready under a fresh token.
        out2, token2 = broker.dequeue([ev.Type], timeout=1)
        assert out2.ID == ev.ID
        assert token2 != token

    def test_unblock_failed(self):
        """(reference: TestBlockedEvals_UnblockFailed): max-plans-
        triggered blocked evals release on unblock_failed, and the job
        can block again afterwards."""
        blocked, broker = self._pair()
        from nomad_tpu.structs.structs import EvalTriggerMaxPlans

        e = self._eval(escaped=True)
        e.TriggeredBy = EvalTriggerMaxPlans
        e2 = self._eval(elig={"v1:123": True})
        e2.TriggeredBy = EvalTriggerMaxPlans
        blocked.block(e)
        blocked.block(e2)
        blocked.unblock_failed()
        assert blocked.stats.TotalBlocked == 0
        assert blocked.stats.TotalEscaped == 0
        assert wait_for(lambda: broker.stats.TotalReady == 2)
        # The SAME job must be trackable again (the jobs-set was
        # cleaned), not misrouted into duplicates.
        blocked.block(e)
        assert blocked.stats.TotalBlocked == 1
        assert blocked.get_duplicates(0) == []
