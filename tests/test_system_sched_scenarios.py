"""System scheduler scenario depth (reference: the system_sched_test.go
grid not yet covered by tests/test_scheduler.py: add-node incremental
placement, alloc-fail metrics, modify in-place vs destructive, deregister,
drain migration)."""

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs.structs import (
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusComplete,
    EvalStatusPending,
    EvalTriggerJobDeregister,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
)


def make_eval(job, trigger=EvalTriggerJobRegister):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = EvalStatusPending
    return ev


def placed(h):
    return [a for p in h.plans for allocs in p.NodeAllocation.values()
            for a in allocs]


def stops(h):
    return [a for p in h.plans for allocs in p.NodeUpdate.values()
            for a in allocs]


class TestSystemSchedScenarios:
    def _register(self, h, job):
        h.upsert("job", job)
        h.process("system", make_eval(job))

    def test_add_node_places_only_there(self):
        """A node joining gets the system job WITHOUT touching existing
        allocs (reference: TestSystemSched_JobRegister_AddNode)."""
        h = Harness()
        for _ in range(4):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        assert len(h.state.allocs_by_job(job.ID)) == 4

        newcomer = mock.node()
        h.upsert("node", newcomer)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerNodeUpdate))
        new_placed = placed(h)
        assert len(new_placed) == 1
        assert new_placed[0].NodeID == newcomer.ID
        assert stops(h) == []  # existing allocs untouched
        assert len(h.state.allocs_by_job(job.ID)) == 5

    def test_alloc_fail_records_metrics(self):
        """Node too small: the eval carries FailedTGAllocs with the
        exhausted dimension (reference: TestSystemSched_JobRegister_
        AllocFail)."""
        h = Harness()
        node = mock.node()
        node.Resources.CPU = 60  # below the system job's ask + reserved
        h.upsert("node", node)
        job = mock.system_job()
        self._register(h, job)
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        assert final.FailedTGAllocs
        metric = next(iter(final.FailedTGAllocs.values()))
        assert metric.NodesEvaluated >= 1

    def test_modify_destructive_replaces_everywhere(self):
        """A changed task config stops and replaces the alloc on every node
        (reference: TestSystemSched_JobModify)."""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        assert len(h.state.allocs_by_job(job.ID)) == 3

        update = job.copy()
        update.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
        update.init_fields()
        h.upsert("job", update)
        h.plans.clear()
        h.process("system", make_eval(update))
        assert len(stops(h)) == 3
        assert len(placed(h)) == 3
        run_allocs = [a for a in h.state.allocs_by_job(job.ID)
                      if a.DesiredStatus == AllocDesiredStatusRun]
        assert len(run_allocs) == 3

    def test_modify_inplace_keeps_allocs(self):
        """A non-destructive change updates in place: no stops, no new
        placements (reference: TestSystemSched_JobModify_InPlace)."""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        before = {a.ID for a in h.state.allocs_by_job(job.ID)}

        update = job.copy()
        from nomad_tpu.structs import Constraint

        update.Constraints = list(update.Constraints) + [Constraint(
            LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")]
        update.init_fields()
        h.upsert("job", update)
        h.plans.clear()
        h.process("system", make_eval(update))
        assert stops(h) == []
        after = {a.ID for a in h.state.allocs_by_job(job.ID)
                 if a.DesiredStatus == AllocDesiredStatusRun}
        assert after == before  # same allocation IDs survive

    def test_deregister_stops_all(self):
        """(reference: TestSystemSched_JobDeregister)"""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        h.state.delete_job(h._next_index(), job.ID)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerJobDeregister))
        assert len(stops(h)) == 3
        live = [a for a in h.state.allocs_by_job(job.ID)
                if a.DesiredStatus == AllocDesiredStatusRun]
        assert live == []

    def test_drain_stops_there_only(self):
        """Draining one node stops its system alloc and leaves the others
        (reference: TestSystemSched_NodeDrain)."""
        h = Harness()
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            h.upsert("node", n)
        job = mock.system_job()
        self._register(h, job)
        victim = nodes[0]
        h.state.update_node_drain(h._next_index(), victim.ID, True)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerNodeUpdate))
        stopped = stops(h)
        assert len(stopped) == 1
        assert stopped[0].NodeID == victim.ID
        assert placed(h) == []  # system jobs don't migrate off-node