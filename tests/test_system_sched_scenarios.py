"""System scheduler scenario depth (reference: the system_sched_test.go
grid not yet covered by tests/test_scheduler.py: add-node incremental
placement, alloc-fail metrics, modify in-place vs destructive, deregister,
drain migration)."""

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs.structs import (
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusComplete,
    EvalStatusPending,
    EvalTriggerJobDeregister,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
)


def make_eval(job, trigger=EvalTriggerJobRegister):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = EvalStatusPending
    return ev


def placed(h):
    return [a for p in h.plans for allocs in p.NodeAllocation.values()
            for a in allocs]


def stops(h):
    return [a for p in h.plans for allocs in p.NodeUpdate.values()
            for a in allocs]


class TestSystemSchedScenarios:
    def _register(self, h, job):
        h.upsert("job", job)
        h.process("system", make_eval(job))

    def test_add_node_places_only_there(self):
        """A node joining gets the system job WITHOUT touching existing
        allocs (reference: TestSystemSched_JobRegister_AddNode)."""
        h = Harness()
        for _ in range(4):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        assert len(h.state.allocs_by_job(job.ID)) == 4

        newcomer = mock.node()
        h.upsert("node", newcomer)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerNodeUpdate))
        new_placed = placed(h)
        assert len(new_placed) == 1
        assert new_placed[0].NodeID == newcomer.ID
        assert stops(h) == []  # existing allocs untouched
        assert len(h.state.allocs_by_job(job.ID)) == 5

    def test_alloc_fail_records_metrics(self):
        """Node too small: the eval carries FailedTGAllocs with the
        exhausted dimension (reference: TestSystemSched_JobRegister_
        AllocFail)."""
        h = Harness()
        node = mock.node()
        node.Resources.CPU = 60  # below the system job's ask + reserved
        h.upsert("node", node)
        job = mock.system_job()
        self._register(h, job)
        final = h.evals[-1]
        assert final.Status == EvalStatusComplete
        assert final.FailedTGAllocs
        metric = next(iter(final.FailedTGAllocs.values()))
        assert metric.NodesEvaluated >= 1

    def test_modify_destructive_replaces_everywhere(self):
        """A changed task config stops and replaces the alloc on every node
        (reference: TestSystemSched_JobModify)."""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        assert len(h.state.allocs_by_job(job.ID)) == 3

        update = job.copy()
        update.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
        update.init_fields()
        h.upsert("job", update)
        h.plans.clear()
        h.process("system", make_eval(update))
        assert len(stops(h)) == 3
        assert len(placed(h)) == 3
        run_allocs = [a for a in h.state.allocs_by_job(job.ID)
                      if a.DesiredStatus == AllocDesiredStatusRun]
        assert len(run_allocs) == 3

    def test_modify_inplace_keeps_allocs(self):
        """A non-destructive change updates in place: no stops, no new
        placements (reference: TestSystemSched_JobModify_InPlace)."""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        before = {a.ID for a in h.state.allocs_by_job(job.ID)}

        update = job.copy()
        from nomad_tpu.structs import Constraint

        update.Constraints = list(update.Constraints) + [Constraint(
            LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")]
        update.init_fields()
        h.upsert("job", update)
        h.plans.clear()
        h.process("system", make_eval(update))
        assert stops(h) == []
        after = {a.ID for a in h.state.allocs_by_job(job.ID)
                 if a.DesiredStatus == AllocDesiredStatusRun}
        assert after == before  # same allocation IDs survive

    def test_deregister_stops_all(self):
        """(reference: TestSystemSched_JobDeregister)"""
        h = Harness()
        for _ in range(3):
            h.upsert("node", mock.node())
        job = mock.system_job()
        self._register(h, job)
        h.state.delete_job(h._next_index(), job.ID)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerJobDeregister))
        assert len(stops(h)) == 3
        live = [a for a in h.state.allocs_by_job(job.ID)
                if a.DesiredStatus == AllocDesiredStatusRun]
        assert live == []

    def test_drain_stops_there_only(self):
        """Draining one node stops its system alloc and leaves the others
        (reference: TestSystemSched_NodeDrain)."""
        h = Harness()
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            h.upsert("node", n)
        job = mock.system_job()
        self._register(h, job)
        victim = nodes[0]
        h.state.update_node_drain(h._next_index(), victim.ID, True)
        h.plans.clear()
        h.process("system", make_eval(job, EvalTriggerNodeUpdate))
        stopped = stops(h)
        assert len(stopped) == 1
        assert stopped[0].NodeID == victim.ID
        assert placed(h) == []  # system jobs don't migrate off-node

class TestSystemPlanChunking:
    """A 10k-alloc system sweep must not monopolize the plan applier when
    other plans are contending: the scheduler streams it in
    SYSTEM_PLAN_CHUNK-alloc chunks (reference frame: plan_apply.go's
    verify/apply overlap; system_sched.go:54-281 commits sweeps whole,
    which is the latency cliff this avoids)."""

    def _sweep_plan(self, n_nodes, per_node=1):
        import logging
        import random

        from nomad_tpu.scheduler.system_sched import SystemScheduler
        from nomad_tpu.state.state_store import StateStore
        from nomad_tpu.structs import compute_node_class
        from nomad_tpu.tensor import TensorIndex

        store = StateStore()
        tindex = TensorIndex.attach(store)
        idx = 1
        for _ in range(n_nodes):
            n = mock.node()
            compute_node_class(n)
            idx += 1
            store.upsert_node(idx, n)
        job = mock.system_job()
        t = job.TaskGroups[0].Tasks[0]
        t.Resources.Networks = []
        t.Services = []
        job.init_fields()
        idx += 1
        store.upsert_job(idx, job)
        ev = make_eval(job)
        sched = SystemScheduler(store, None, tindex,
                                logging.getLogger("test"),
                                rng=random.Random(1))
        sched.eval = ev
        return sched, job, ev

    def test_contended_sweep_chunks_and_merges(self):
        from nomad_tpu.scheduler import system_sched as ss
        from nomad_tpu.structs import PlanResult

        class Capture:
            def __init__(self, depth):
                self.depth = depth
                self.batches = []

            def plan_queue_depth(self):
                return self.depth

            def _result(self, plan):
                r = PlanResult()
                r.NodeUpdate = dict(plan.NodeUpdate)
                r.NodeAllocation = dict(plan.NodeAllocation)
                r.AllocIndex = len(self.batches)
                return r

            def submit_plan(self, plan):
                self.batches.append([plan])
                return self._result(plan), None

            def submit_plans(self, plans):
                self.batches.append(list(plans))
                return [self._result(p) for p in plans], None

            def update_eval(self, e): ...
            def create_eval(self, e): ...
            def reblock_eval(self, e): ...

        n_nodes = ss.SYSTEM_PLAN_CHUNK + 64  # 2 chunks when contended
        for depth, want_plans in ((0, 1), (3, 2)):
            sched, job, ev = self._sweep_plan(n_nodes)
            planner = Capture(depth)
            sched.planner = planner
            sched._process()
            assert len(planner.batches) == 1
            plans = planner.batches[0]
            assert len(plans) == want_plans, (depth, len(plans))
            total = sum(len(v) for p in plans
                        for v in p.NodeAllocation.values())
            assert total == n_nodes
            if want_plans > 1:
                # Node boundaries preserved: no node split across chunks,
                # and the merged result covers the whole sweep.
                seen = set()
                for p in plans:
                    for nid in p.NodeAllocation:
                        assert nid not in seen
                        seen.add(nid)
                assert len(seen) == n_nodes
                assert sum(
                    len(v) for v in
                    sched.plan_result.NodeAllocation.values()) == n_nodes

    def test_interactive_eval_interleaves_with_sweep(self, monkeypatch):
        """Live server: a small service eval submitted behind a fleet-wide
        system sweep completes without waiting for the whole sweep. The
        chunk size is pinned low and the contention check forced on so the
        sweep actually exercises the live submit_plans pipelining
        (enqueue-all, wait-in-order) rather than the monolithic path."""
        from nomad_tpu import mock as m
        from nomad_tpu.scheduler import system_sched as ss
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.server.worker import Worker
        from nomad_tpu.structs import compute_node_class

        from helpers import wait_for

        monkeypatch.setattr(ss, "SYSTEM_PLAN_CHUNK", 16)
        monkeypatch.setattr(Worker, "plan_queue_depth", lambda self: 1)
        srv = Server(ServerConfig(num_schedulers=2,
                                  pipelined_scheduling=True,
                                  scheduler_window=8,
                                  min_heartbeat_ttl=3600.0,
                                  heartbeat_grace=3600.0))
        srv.establish_leadership()
        try:
            for _ in range(64):
                n = m.node()
                compute_node_class(n)
                srv.node_register(n)
            sysjob = m.system_job()
            t = sysjob.TaskGroups[0].Tasks[0]
            t.Resources.DiskMB = 300
            t.Resources.Networks = []
            t.Services = []
            sys_eval = srv.job_register(sysjob)[0]
            svc = m.job()
            svc.TaskGroups[0].Count = 2
            t = svc.TaskGroups[0].Tasks[0]
            t.Resources.CPU = 20
            t.Resources.MemoryMB = 32
            t.Resources.Networks = []
            t.Services = []
            svc_eval = srv.job_register(svc)[0]
            wait_for(lambda: all(
                (e := srv.state.eval_by_id(i)) is not None
                and e.Status == EvalStatusComplete
                for i in (sys_eval, svc_eval)), timeout=60)
            assert len(list(srv.state.allocs_by_eval(sys_eval))) == 64
            assert len(list(srv.state.allocs_by_eval(svc_eval))) == 2
        finally:
            srv.shutdown()
