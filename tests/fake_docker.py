"""A stub docker CLI for driver lifecycle tests.

The docker driver shells out to the docker CLI (run/wait/logs/stop/rm/
rmi/stats/exec/version), so the test double is a fake `docker`
executable, not an HTTP daemon fake (the reference gates its docker
suite on a live daemon — client/driver/docker_test.go — which this
environment does not have; the stub lets the full lifecycle run
unconditionally in CI).

Containers are simulated from a state directory (env FAKE_DOCKER_STATE):
one JSON file per container, plus invocations.jsonl recording every CLI
call's argv and daemon-connection env (DOCKER_HOST / DOCKER_CERT_PATH /
DOCKER_TLS_VERIFY) so tests can assert endpoint/TLS options propagate.

Image-name conventions drive behavior:
  fake/short   exits 0 after ~0.2s; logs one stdout and one stderr line
               (including any command/args, to assert interpolation)
  fake/long    runs until `docker stop` (exit 137)
  fake/fail    exits 7 immediately
"""

import json
import os
import sys
import time
import uuid


def _state_dir() -> str:
    d = os.environ["FAKE_DOCKER_STATE"]
    os.makedirs(d, exist_ok=True)
    return d


def _record(argv):
    keys = ("DOCKER_HOST", "DOCKER_CERT_PATH", "DOCKER_TLS_VERIFY")
    entry = {"argv": argv,
             "env": {k: os.environ[k] for k in keys if k in os.environ}}
    with open(os.path.join(_state_dir(), "invocations.jsonl"), "a") as f:
        f.write(json.dumps(entry) + "\n")


def _cpath(cid: str) -> str:
    return os.path.join(_state_dir(), f"{cid}.json")


def _load(cid: str) -> dict:
    matches = [f for f in os.listdir(_state_dir())
               if f.endswith(".json") and f.startswith(cid)]
    if not matches:
        raise SystemExit(f"Error: No such container: {cid}")
    with open(os.path.join(_state_dir(), matches[0])) as f:
        return json.load(f)


def _save(c: dict) -> None:
    with open(_cpath(c["id"]), "w") as f:
        json.dump(c, f)


def _done(c: dict):
    """(finished, exit_code) under the simulated clock."""
    if c.get("stopped_at") is not None:
        return True, c["exit_code"]
    if time.time() >= c["created"] + c["duration"]:
        return True, c["exit_code"]
    return False, None


def cmd_run(argv):
    # argv: flags... image [command args...]; parse the flags the driver
    # emits, collect everything for assertions.
    flags = {"volumes": [], "env": [], "labels": [], "ports": []}
    i = 0
    rest = []
    while i < len(argv):
        a = argv[i]
        if a == "-d":
            i += 1
        elif a == "-v":
            flags["volumes"].append(argv[i + 1]); i += 2
        elif a == "-e":
            flags["env"].append(argv[i + 1]); i += 2
        elif a == "--label":
            flags["labels"].append(argv[i + 1]); i += 2
        elif a == "-p":
            flags["ports"].append(argv[i + 1]); i += 2
        elif a == "--network":
            flags["network"] = argv[i + 1]; i += 2
        elif a == "--memory":
            flags["memory"] = argv[i + 1]; i += 2
        elif a == "--cpu-shares":
            flags["cpu_shares"] = argv[i + 1]; i += 2
        else:
            rest.append(a); i += 1
    image, cmdargs = rest[0], rest[1:]
    cid = uuid.uuid4().hex
    c = {"id": cid, "image": image, "cmd": cmdargs, "flags": flags,
         "created": time.time(), "stopped_at": None, "removed": False}
    if image.startswith("fake/long"):
        c.update(duration=3600.0, exit_code=0)
    elif image.startswith("fake/fail"):
        c.update(duration=0.0, exit_code=7)
    else:
        c.update(duration=0.2, exit_code=0)
    c["stdout"] = f"out:{image}:{' '.join(cmdargs)}\n"
    c["stderr"] = f"err:{image}\n"
    _save(c)
    print(cid)


def cmd_wait(cid):
    while True:
        c = _load(cid)
        finished, code = _done(c)
        if finished:
            print(code)
            return
        time.sleep(0.05)


def cmd_logs(argv):
    follow = "-f" in argv
    args = [a for a in argv if not a.startswith("-")
            and not a.replace(".", "").isdigit()]
    cid = args[-1]
    c = _load(cid)
    sys.stdout.write(c["stdout"])
    sys.stderr.write(c["stderr"])
    sys.stdout.flush()
    sys.stderr.flush()
    if follow:
        while not _done(_load(cid))[0]:
            time.sleep(0.05)


def cmd_stop(argv):
    cid = argv[-1]
    c = _load(cid)
    if not _done(c)[0]:
        c["exit_code"] = 137
    c["stopped_at"] = time.time()
    _save(c)
    print(c["id"])


def cmd_rm(cid):
    c = _load(cid)
    c["removed"] = True
    _save(c)
    print(c["id"])


def cmd_stats(argv):
    ids = [a for a in argv if not a.startswith("-")
           and not a.startswith("{{")]
    for cid in ids:
        c = _load(cid)
        if not _done(c)[0]:
            print(f"{c['id'][:12]} 5.00% 10MiB / 256MiB")


def cmd_exec(argv):
    cid = argv[0]
    rest = argv[1:]
    if rest and rest[0] == "timeout":
        rest = rest[2:]  # strip `timeout N`
    _load(cid)  # must exist
    print(f"exec:{' '.join(rest)}")


def main():
    argv = sys.argv[1:]
    # Strip the global --config flag (auth): copy its config.json into
    # state so tests can assert the credentials existed AT CALL TIME
    # (the driver deletes the directory right after `docker run`).
    if argv and argv[0] == "--config":
        cfg = os.path.join(argv[1], "config.json")
        if os.path.exists(cfg):
            with open(cfg) as f:
                auth = f.read()
            with open(os.path.join(_state_dir(), "last_auth.json"),
                      "w") as f:
                f.write(auth)
        argv = argv[2:]
    _record(argv)
    cmd, rest = argv[0], argv[1:]
    if cmd == "version":
        print("1.11.fake")
    elif cmd == "run":
        cmd_run(rest)
    elif cmd == "wait":
        cmd_wait(rest[-1])
    elif cmd == "logs":
        cmd_logs(rest)
    elif cmd == "stop":
        cmd_stop(rest)
    elif cmd == "rm":
        cmd_rm(rest[-1])
    elif cmd == "rmi":
        print(rest[-1])
    elif cmd == "stats":
        cmd_stats(rest)
    elif cmd == "exec":
        cmd_exec(rest)
    else:
        raise SystemExit(f"fake docker: unknown command {cmd}")


if __name__ == "__main__":
    main()
