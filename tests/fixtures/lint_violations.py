"""Seeded lint violations — one (or more) per checker. NEVER imported;
tests/test_analysis_lint.py and the `nomad-tpu lint` CLI parse it to
prove every checker fires. Line comments name the expected checker id.
"""

import threading
import time

from nomad_tpu.analysis import guarded_by


class BadStore:
    _concurrency = guarded_by("_lock", "_items")

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def unlocked_access(self):
        return len(self._items)          # guarded_by

    def sleepy_critical_section(self):
        with self._lock:
            time.sleep(0.5)              # lock_blocking


def hand_rolled_retry():
    while True:
        time.sleep(1.0)                  # retry


def anonymous_thread():
    threading.Thread(target=hand_rolled_retry).start()   # thread (x2:
    #                             no name=, untracked non-daemon)


def silent_swallow():
    try:
        hand_rolled_retry()
    except Exception:
        pass                             # swallow


def undeclared_failpoint(failpoints):
    failpoints.fire("fixture.not.a.declared.site")       # failpoint_site


def bad_metric_key(metrics):
    metrics.incr_counter(("Wrong-Scheme", "X"), 1)       # metric_key


def bad_span_name(trace):
    with trace.span("NotDotted"):        # trace_key
        pass


def bad_event_literals(new_event, ev):
    new_event("NotATopic", "NodeRegistered", "k")        # event_schema
    new_event("Node", "NotAType", "k")                   # event_schema
    new_event("Job", "NodeRegistered", "k")              # event_schema
    return ev["Topic"] == "Bogus"                        # event_schema
