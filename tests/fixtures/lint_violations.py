"""Seeded lint violations — one (or more) per checker. NEVER imported;
tests/test_analysis_lint.py and the `nomad-tpu lint` CLI parse it to
prove every checker fires. Line comments name the expected checker id.
"""

import random
import threading
import time
from uuid import uuid4

from nomad_tpu.analysis import guarded_by


class BadStore:
    _concurrency = guarded_by("_lock", "_items")

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def unlocked_access(self):
        return len(self._items)          # guarded_by

    def sleepy_critical_section(self):
        with self._lock:
            time.sleep(0.5)              # lock_blocking


def hand_rolled_retry():
    while True:
        time.sleep(1.0)                  # retry


def anonymous_thread():
    threading.Thread(target=hand_rolled_retry).start()   # thread (x2:
    #                             no name=, untracked non-daemon)


def silent_swallow():
    try:
        hand_rolled_retry()
    except Exception:
        pass                             # swallow


def undeclared_failpoint(failpoints):
    failpoints.fire("fixture.not.a.declared.site")       # failpoint_site


def bad_metric_key(metrics):
    metrics.incr_counter(("Wrong-Scheme", "X"), 1)       # metric_key


def bad_span_name(trace):
    with trace.span("NotDotted"):        # trace_key
        pass


def bad_event_literals(new_event, ev):
    new_event("NotATopic", "NodeRegistered", "k")        # event_schema
    new_event("Node", "NotAType", "k")                   # event_schema
    new_event("Job", "NodeRegistered", "k")              # event_schema
    return ev["Topic"] == "Bogus"                        # event_schema


# -------------------------------------------------------------- apply_pure
# Outside the package tree, apply/restore-named functions are roots, so
# the fixture proves the checker's reachability modes without importing
# the real FSM.
def _stamp_payload(payload):
    payload["Jitter"] = random.random()  # apply_pure (2-hop indirect)
    return payload


class ImpureFixtureFSM:
    def apply(self, index, payload):
        payload["AppliedAt"] = time.time()   # apply_pure (direct)
        self._dispatch(index, _stamp_payload(payload))

    def _dispatch(self, index, payload):
        payload["ID"] = str(uuid4())         # apply_pure (method dispatch)

    def suppressed_witness(self, index):
        # Reached from apply via _dispatch? No — reached from restore
        # below; the allow() must silence it (proven by the callgraph
        # tests, not the firing test).
        # lint: allow(apply_pure, fixture demonstrates a reasoned allow)
        return time.monotonic()


def restore_fixture(fsm):
    return fsm.suppressed_witness(0)


def unreachable_nondeterminism():
    """No apply/restore root reaches this — it must NOT fire."""
    return time.time_ns() + id(object())
