"""Gossip-driven cluster formation and multi-region federation tests
(reference shapes: nomad/serf.go maybeBootstrap + nodeJoin/nodeFailed,
nomad/leader.go:421-459 reconcileMember, rpc.go:223-242 forwardRegion;
test style: in-process loopback clusters of nomad/server_test.go)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.gossip import GossipConfig
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.rpc.pool import ConnPool
from nomad_tpu.server.server import ServerConfig
from nomad_tpu.structs import to_dict


from helpers import wait_for  # noqa: E402


FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)


def boot(name, region="global", expect=1, join=None, num_schedulers=0):
    cs = ClusterServer(ServerConfig(
        node_id="", region=region, num_schedulers=num_schedulers,
        bootstrap_expect=expect))
    cs.connect([], raft_config=FAST)  # no static peers: gossip drives raft
    cs.start()
    cs.enable_gossip(name, join=join, gossip_config=GossipConfig.fast())
    return cs


def gossip_addr(cs):
    ml = cs.membership.memberlist
    return f"{ml.addr}:{ml.port}"


def leader_of(nodes):
    for n in nodes:
        if n.server.is_leader() and n.server._leader:
            return n
    return None


class TestGossipBootstrap:
    def test_three_servers_form_cluster_via_gossip(self):
        """bootstrap-expect=3: no server elects until all three have
        discovered each other; then exactly one leader emerges."""
        nodes = [boot("s0", expect=3)]
        try:
            # Alone, a 3-expect server must stay dormant.
            time.sleep(0.5)
            assert leader_of(nodes) is None
            nodes.append(boot("s1", expect=3, join=[gossip_addr(nodes[0])]))
            nodes.append(boot("s2", expect=3, join=[gossip_addr(nodes[0])]))
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            addrs = sorted(n.addr for n in nodes)
            assert wait_for(
                lambda: sorted(leader.server.raft.peers) == addrs)
            # The whole cluster replicates: register a node through any
            # member and observe it on a follower's store.
            follower = [n for n in nodes if n is not leader][0]
            resp = follower.endpoints.handle(
                "Node.Register", {"Node": to_dict(mock.node())})
            assert resp["Index"] > 0
            assert wait_for(lambda: len(
                follower.server.state.nodes()) == 1)
        finally:
            for n in nodes:
                n.shutdown()

    def test_late_joiner_added_as_raft_peer(self):
        nodes = [boot("s0", expect=1)]
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            late = boot("s3", expect=0, join=[gossip_addr(nodes[0])])
            nodes.append(late)
            leader = leader_of(nodes)
            assert wait_for(
                lambda: late.addr in leader.server.raft.peers)
            # the joiner eventually becomes a voting member (electable) by
            # applying the replicated Config entry that names it
            assert wait_for(lambda: late.server.raft.node.electable)
        finally:
            for n in nodes:
                n.shutdown()

    def test_failed_server_removed_from_raft(self):
        nodes = [boot("s0", expect=3)]
        nodes.append(boot("s1", expect=3, join=[gossip_addr(nodes[0])]))
        nodes.append(boot("s2", expect=3, join=[gossip_addr(nodes[0])]))
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            leader = leader_of(nodes)
            victim = [n for n in nodes if n is not leader][0]
            victim.shutdown()
            assert wait_for(
                lambda: victim.addr not in leader_of(nodes).server.raft.peers
                if leader_of(nodes) else False,
                timeout=20.0)
        finally:
            for n in nodes:
                n.shutdown()

    def test_follower_reads_are_consistent(self):
        """A read on a follower right after a write must see it: reads
        forward to the leader unless AllowStale (reference: the s.forward
        prologue on every read endpoint + QueryOptions.AllowStale)."""
        nodes = [boot("s0", expect=3)]
        nodes.append(boot("s1", expect=3, join=[gossip_addr(nodes[0])]))
        nodes.append(boot("s2", expect=3, join=[gossip_addr(nodes[0])]))
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            # Read-your-writes holds against a STABLE leader; an election
            # mid-sequence (suite-load jitter) legitimately defers applies,
            # so retry the whole write+read pair if leadership moved.
            for _ in range(3):
                leader = leader_of(nodes)
                if leader is None:
                    time.sleep(0.2)
                    continue
                follower = [n for n in nodes if n is not leader][0]
                job = mock.job()
                resp = follower.endpoints.handle("Job.Register",
                                                 {"Job": to_dict(job)})
                eval_id = resp["EvalID"]
                # Immediately, through the SAME follower, no AllowStale.
                got = follower.endpoints.handle("Eval.GetEval",
                                                {"EvalID": eval_id})
                if got["Eval"] is None and leader_of(nodes) is not leader:
                    continue  # leadership moved mid-pair: retry
                assert got["Eval"] is not None
                assert got["Eval"]["ID"] == eval_id
                break
            else:
                raise AssertionError("no stable leadership window")
        finally:
            for n in nodes:
                n.shutdown()

    def test_server_members_rpc(self):
        nodes = [boot("s0", expect=1)]
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            members = nodes[0].endpoints.handle("Agent.Members", {})
            assert len(members) == 1
            assert members[0]["Name"] == "s0.global"
            assert members[0]["Status"] == "alive"
        finally:
            for n in nodes:
                n.shutdown()


class TestFederation:
    def test_cross_region_job_submission(self):
        """A job for region A submitted to a region-B server is forwarded
        over the gossip-populated region route and lands in region A
        (reference: forwardRegion, nomad/rpc.go:223-242)."""
        a = boot("a0", region="alpha", expect=1)
        b = None
        pool = ConnPool()
        try:
            assert wait_for(lambda: a.server.is_leader())
            b = boot("b0", region="beta", expect=1,
                     join=[gossip_addr(a)])
            assert wait_for(lambda: b.server.is_leader())
            # WAN pool converged: each side routes to the other's region
            assert wait_for(lambda: a.membership.region_router("beta")
                            is not None)
            assert wait_for(lambda: b.membership.region_router("alpha")
                            is not None)

            job = mock.job()
            job.Region = "alpha"
            resp = pool.call(b.addr, "Job.Register",
                             {"Job": to_dict(job), "Region": "alpha"})
            assert resp["Index"] > 0
            assert a.server.state.job_by_id(job.ID) is not None
            assert b.server.state.job_by_id(job.ID) is None

            regions = pool.call(b.addr, "Region.List", {})
            assert regions == ["alpha", "beta"]
        finally:
            pool.close()
            a.shutdown()
            if b is not None:
                b.shutdown()

    def test_networked_agents_form_cluster(self):
        """Two full server agents (HTTP + RPC + gossip) federate through
        the agent layer: members visible over /v1/agent/members, a client
        agent schedules against them over wire RPC."""
        from nomad_tpu.agent import Agent, AgentConfig
        from nomad_tpu.api import Client as ApiClient

        a1 = Agent(AgentConfig(server_enabled=True, http_port=0,
                               rpc_port=0, serf_port=0, bootstrap_expect=2,
                               node_name="n1", num_schedulers=0))
        a1.start()
        ml = a1.cluster.membership.memberlist
        a2 = Agent(AgentConfig(server_enabled=True, http_port=0,
                               rpc_port=0, serf_port=0, bootstrap_expect=2,
                               node_name="n2", num_schedulers=0,
                               start_join=[f"{ml.addr}:{ml.port}"]))
        a2.start()
        try:
            assert wait_for(lambda: sum(
                1 for a in (a1, a2)
                if a.server.is_leader() and a.server._leader) == 1)
            api = ApiClient(f"http://127.0.0.1:{a1.http.port}")
            members = api.agent.members()
            assert sorted(m["Name"] for m in members) == [
                "n1.global", "n2.global"]
            assert all(m["Status"] == "alive" for m in members)
            # servers list is the gossip-discovered RPC addresses
            assert len(api.agent.servers()) == 2
        finally:
            a2.shutdown()
            a1.shutdown()

    def test_force_leave_marks_member_left(self):
        a = boot("a0", expect=1)
        b = boot("b0", expect=0, join=[gossip_addr(a)])
        try:
            assert wait_for(lambda: len(a.membership.members()) == 2)
            b.membership.memberlist.shutdown()  # hard kill, no leave
            resp = a.endpoints.handle("Agent.ForceLeave",
                                      {"Node": "b0.global"})
            assert resp["Ok"]
            assert wait_for(lambda: any(
                m["Name"] == "b0.global" and m["Status"] in ("left", "dead")
                for m in a.membership.members()))
        finally:
            a.shutdown()
            b.shutdown()


class TestBootstrapProbe:
    """Status.RaftStats is the bootstrap-expect probe (reference:
    maybeBootstrap probing peers before forming a cluster,
    nomad/serf.go:104-130). Round-3 regression class: the raft peer set
    always contains self, so peer-set truthiness made every VIRGIN server
    report Bootstrapped=true — three virgin servers all deferred to each
    other forever and no cluster formed."""

    def test_virgin_server_reports_not_bootstrapped(self):
        cs = boot("probe-v0", expect=3)
        try:
            resp = cs.endpoints.handle("Status.RaftStats", {})
            assert resp["Bootstrapped"] is False
            assert resp["Stats"]["num_peers"] == 1  # self only
            assert not resp["Stats"]["configured"]
        finally:
            cs.shutdown()

    def test_live_cluster_reports_bootstrapped(self):
        cs = boot("probe-l0", expect=1)
        try:
            assert wait_for(lambda: leader_of([cs]) is not None)
            resp = cs.endpoints.handle("Status.RaftStats", {})
            assert resp["Bootstrapped"] is True
            # A live node must refuse a second bootstrap.
            assert cs.server.raft.bootstrap_cluster(["bogus:1"]) is False
        finally:
            cs.shutdown()

    def test_virgin_joiner_defers_to_live_cluster(self):
        """1 virgin + 1 live cluster: the virgin server meets its expect
        count but must NOT form a second cluster — it defers on the probe
        and is admitted by the leader's reconcile instead."""
        nodes = [boot("probe-a", expect=2)]
        nodes.append(boot("probe-b", expect=2,
                          join=[gossip_addr(nodes[0])]))
        try:
            assert wait_for(lambda: leader_of(nodes) is not None)
            late = boot("probe-c", expect=2, join=[gossip_addr(nodes[0])])
            nodes.append(late)
            addrs = sorted(n.addr for n in nodes)
            # Admitted via Config entry, not a fresh bootstrap: all three
            # converge on ONE cluster with ONE shared leader.
            for n in nodes:
                assert wait_for(
                    lambda n=n: sorted(n.server.raft.peers) == addrs)
            assert wait_for(
                lambda: len({n.server.raft.leader_id for n in nodes}) == 1
                and nodes[0].server.raft.leader_id)
            assert sum(1 for n in nodes
                       if n.server.is_leader() and n.server._leader) == 1
        finally:
            for n in nodes:
                n.shutdown()
