"""Scenario matrix round 2 (toward the reference's generic_sched_test.go
coverage): full rolling-update eval CHAINS driven to convergence, AllAtOnce
gang commits under contention at the plan applier, and distinct_hosts at
kernel scale.
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.server.fsm import FSM, DevRaft, MessageType
from nomad_tpu.server.plan_apply import PlanApplier
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.structs import Constraint, Plan, UpdateStrategy
from nomad_tpu.structs.structs import (
    SECOND,
    EvalStatusComplete,
    EvalStatusPending,
    EvalTriggerJobRegister,
    EvalTriggerRollingUpdate,
)


def make_eval(job, trigger=EvalTriggerJobRegister):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = EvalStatusPending
    return ev


class TestRollingUpdateChain:
    def test_destructive_update_chains_to_convergence(self):
        """A destructive update of a 6-count group with max_parallel=2
        replaces exactly 2 per pass; each pass chains a rolling-update
        follow-up eval (NextEval/PreviousEval linked, stagger wait) until
        every alloc runs the new version (reference:
        TestServiceSched_JobModify_Rolling + NextRollingEval,
        structs.go:2810)."""
        h = Harness()
        for _ in range(8):
            h.upsert("node", mock.node())
        job = mock.job()
        job.TaskGroups[0].Count = 6
        job.Update = UpdateStrategy(Stagger=10 * SECOND, MaxParallel=2)
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))
        assert len([a for a in h.state.allocs_by_job(job.ID)
                    if not a.terminal_status()]) == 6

        # Destructive change: bump the task's resources.
        job2 = job.copy()
        job2.TaskGroups[0].Tasks[0].Resources.CPU += 100
        job2.init_fields()
        h.upsert("job", job2)

        ev = make_eval(job2)
        rounds = 0
        chain = []
        while True:
            h.creates.clear()
            h.process("service", ev)
            rounds += 1
            follow = [e for e in h.creates
                      if e.TriggeredBy == EvalTriggerRollingUpdate]
            if not follow:
                break
            assert len(follow) == 1
            nxt = follow[0]
            # Chain links (reference: NextRollingEval sets PreviousEval).
            assert nxt.Wait == 10 * SECOND
            assert nxt.PreviousEval == ev.ID
            chain.append(nxt.ID)
            assert rounds < 10, "rolling chain never converged"
            ev = nxt

        # 6 allocs / 2 per pass = 3 destructive passes; the last pass's
        # follow-up sees nothing left and completes without a successor.
        assert rounds >= 3
        live = [a for a in h.state.allocs_by_job(job.ID)
                if not a.terminal_status()]
        assert len(live) == 6
        new_cpu = job2.TaskGroups[0].Tasks[0].Resources.CPU
        for a in live:
            res = a.TaskResources[job2.TaskGroups[0].Tasks[0].Name]
            assert res.CPU == new_cpu, "old-version alloc survived the roll"


class TestAllAtOnceContention:
    def test_racing_gangs_one_commits_whole_other_commits_nothing(self):
        """Two AllAtOnce gang plans race over capacity that fits only one
        gang: the applier's verification must commit one gang COMPLETELY
        and the loser NOT AT ALL — a partial gang is worse than none
        (reference: Plan.AllAtOnce, structs.go:2845-2928 +
        plan_apply.go:194-316 clearing the whole result)."""
        fsm = FSM()
        raft = DevRaft(fsm)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        applier.start()
        try:
            nodes = []
            for _ in range(4):
                node = mock.node()
                node.Resources.CPU = 500
                node.Reserved = None
                raft.apply(MessageType.NodeRegister, {"Node": node})
                nodes.append(node)

            def gang_plan():
                plan = Plan(EvalID=mock.eval().ID, Priority=50,
                            AllAtOnce=True)
                for node in nodes:
                    alloc = mock.alloc()
                    alloc.NodeID = node.ID
                    alloc.Resources.CPU = 400  # 4x400: only one gang fits
                    alloc.Resources.Networks = []
                    alloc.TaskResources = {}
                    plan.NodeAllocation[node.ID] = [alloc]
                return plan

            pendings = [queue.enqueue(gang_plan()) for _ in range(2)]
            results = [p.wait(timeout=10) for p in pendings]

            committed = [r for r in results if r.NodeAllocation]
            empty = [r for r in results if not r.NodeAllocation]
            assert len(committed) == 1, "exactly one gang must win"
            assert len(empty) == 1
            # Winner committed on ALL nodes; loser carries RefreshIndex.
            assert len(committed[0].NodeAllocation) == len(nodes)
            assert empty[0].RefreshIndex > 0
            # State holds exactly one gang's worth.
            live = [a for a in fsm.state.allocs()
                    if not a.terminal_status()]
            assert len(live) == len(nodes)
        finally:
            applier.stop()
            queue.set_enabled(False)

    def test_gang_partial_infeasible_commits_nothing(self):
        """One node of the gang is already full: the whole gang is refused
        even though 3 of 4 placements fit."""
        fsm = FSM()
        raft = DevRaft(fsm)
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        applier.start()
        try:
            nodes = []
            for i in range(4):
                node = mock.node()
                node.Resources.CPU = 500 if i else 100  # node 0 too small
                node.Reserved = None
                raft.apply(MessageType.NodeRegister, {"Node": node})
                nodes.append(node)
            plan = Plan(EvalID=mock.eval().ID, Priority=50, AllAtOnce=True)
            for node in nodes:
                alloc = mock.alloc()
                alloc.NodeID = node.ID
                alloc.Resources.CPU = 400
                alloc.Resources.Networks = []
                alloc.TaskResources = {}
                plan.NodeAllocation[node.ID] = [alloc]
            result = queue.enqueue(plan).wait(timeout=10)
            assert not result.NodeAllocation
            assert not [a for a in fsm.state.allocs()
                        if not a.terminal_status()]
        finally:
            applier.stop()
            queue.set_enabled(False)


class TestDistinctHostsAtScale:
    def test_distinct_hosts_512_nodes_all_unique(self):
        """distinct_hosts at kernel scale: 512-count group over 512 nodes
        places every instance on a unique host through the batched device
        scan (reference semantics: ProposedAllocConstraintIterator,
        feasible.go:145-242)."""
        h = Harness()
        node_ids = set()
        for _ in range(512):
            node = mock.node()
            h.upsert("node", node)
            node_ids.add(node.ID)
        job = mock.job()
        job.Constraints.append(Constraint(Operand="distinct_hosts"))
        tg = job.TaskGroups[0]
        tg.Count = 512
        task = tg.Tasks[0]
        task.Resources.CPU = 20
        task.Resources.MemoryMB = 32
        task.Resources.Networks = []
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))

        live = [a for a in h.state.allocs_by_job(job.ID)
                if not a.terminal_status()]
        assert len(live) == 512
        hosts = [a.NodeID for a in live]
        assert len(set(hosts)) == 512, "duplicate host under distinct_hosts"
        assert set(hosts) <= node_ids

    def test_distinct_hosts_overflow_blocks_remainder(self):
        """Count exceeds the node pool: exactly one per host places, the
        remainder fails placement and blocks."""
        h = Harness()
        for _ in range(16):
            h.upsert("node", mock.node())
        job = mock.job()
        job.Constraints.append(Constraint(Operand="distinct_hosts"))
        tg = job.TaskGroups[0]
        tg.Count = 24
        task = tg.Tasks[0]
        task.Resources.CPU = 20
        task.Resources.MemoryMB = 32
        task.Resources.Networks = []
        job.init_fields()
        h.upsert("job", job)
        h.process("service", make_eval(job))

        live = [a for a in h.state.allocs_by_job(job.ID)
                if not a.terminal_status()]
        assert len(live) == 16
        assert len({a.NodeID for a in live}) == 16
        final = h.evals[-1]
        assert final.FailedTGAllocs
        tg_metric = final.FailedTGAllocs[tg.Name]
        assert tg_metric.CoalescedFailures == 24 - 16 - 1
