"""ISSUE 12 equivalence gate: the shard-local keyed mesh pipeline.

The sharded keyed program (kernels._place_batch_keyed_mesh: per-shard
top-k -> winner-row exchange -> lead-device merge/replay) must produce
the SAME placements as the single-device keyed kernel — bit-for-bit —
and the same selections as the exact monolithic scan: identical chosen
rows, scores, and success masks, including lowest-global-row tie-breaks
that span shard boundaries and windows with failed placements. A
server-level case forces a fallback record mid-stream and asserts the
mesh-served placements still match single-device serving.

Runs on the 8-virtual-CPU-device mesh conftest forces
(XLA_FLAGS=--xla_force_host_platform_device_count=8), so tier-1 covers
the mesh path without a TPU.
"""

import pickle
import random

import numpy as np
import pytest

import jax

from nomad_tpu.parallel import scheduling_mesh
from nomad_tpu.scheduler import kernels

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _mesh():
    return scheduling_mesh(jax.devices()[:8])


def _inputs(n=2048, t=4, seed=42):
    rng = np.random.default_rng(seed)
    return dict(
        capacity=rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
        score_cap=rng.uniform(800, 3800, (n, 2)).astype(np.float32),
        usage=rng.uniform(0, 200, (n, 5)).astype(np.float32),
        tg_masks=rng.random((t, n)) < 0.9,
        job_counts=np.zeros(n, np.int32),
        key_demands=rng.uniform(5, 40, (t, 5)).astype(np.float32),
        noise=(rng.random(n) * 1e-3).astype(np.float32),
        banned0=np.zeros(n, bool),
    )


def _window(d, p=64, n_valid=60, seed=3):
    rng = np.random.default_rng(seed)
    t = d["key_demands"].shape[0]
    tg_ids = rng.integers(0, t, p).astype(np.int32)
    valid = np.zeros(p, bool)
    valid[:n_valid] = True
    reset = np.zeros(p, bool)
    reset[::16] = True
    return tg_ids, valid, reset, n_valid


# Hoisted scalars: the mesh warm path pins every static input by OBJECT
# identity (in production the worker's content-addressed device cache
# guarantees it); a fresh np.float32 per window would force cold rebuilds.
_PENALTY = np.float32(10.0)
_DISTINCT = np.asarray(False)


def _run_chain(mesh, d, windows, p=64, n_valid=60):
    """Chain `windows` keyed windows (cold + warm on the mesh) and return
    (packed per window, final usage)."""
    tg_ids, valid, reset, nv = _window(d, p, n_valid)
    usage = d["usage"]
    outs = []
    for _ in range(windows):
        res = kernels.place_batch_keyed(
            mesh, d["capacity"], d["score_cap"], usage, d["tg_masks"],
            d["job_counts"], d["key_demands"], tg_ids, valid, d["noise"],
            _PENALTY, _DISTINCT, d["banned0"], reset, nv)
        outs.append(np.asarray(res.packed))
        usage = res.usage_after
    final = np.asarray(usage)  # MeshChain.__array__ folds the ring
    return outs, final


class TestMeshKeyedEquivalence:
    def test_sharded_matches_single_device_bit_for_bit(self):
        """Chained cold + warm mesh windows == the single-device keyed
        kernel on every output: chosen rows, scores, n_feasible, success
        masks, and the final chained usage."""
        kernels.mesh_stats_drain()
        d = _inputs()
        one, u_one = _run_chain(None, d, windows=4)
        shd, u_shd = _run_chain(_mesh(), d, windows=4)
        for w, (a, b) in enumerate(zip(one, shd)):
            np.testing.assert_array_equal(a, b, err_msg=f"window {w}")
            # Success mask: same compact semantics the drain consumes.
            tg_ids, valid, _, nv = _window(d)
            ok_a = kernels.compact_host(a, nv).ok
            ok_b = kernels.compact_host(b, nv).ok
            assert ok_a == ok_b
        np.testing.assert_array_equal(u_one, u_shd)
        stats = kernels.mesh_stats_drain()
        assert stats["windows"] == 4 and stats["warm_windows"] == 3, (
            "the chain did not exercise the warm pool path", stats)

    def test_matches_exact_scan_selection(self):
        """Chosen rows and n_feasible match the monolithic scan (the
        exact oracle) across a multi-eval window."""
        d = _inputs(n=1024, seed=11)
        tg_ids, valid, reset, nv = _window(d, p=128, n_valid=120, seed=5)
        demands = d["key_demands"][tg_ids] * valid[:, None]
        ref = kernels.place_batch_multi(
            d["capacity"], d["score_cap"], d["usage"], d["tg_masks"],
            d["job_counts"], demands, tg_ids, valid, d["noise"],
            np.float32(10.0), np.asarray(False), d["banned0"], reset)
        res = kernels.place_batch_keyed(
            _mesh(), d["capacity"], d["score_cap"], d["usage"],
            d["tg_masks"], d["job_counts"], d["key_demands"], tg_ids,
            valid, d["noise"], np.float32(10.0), np.asarray(False),
            d["banned0"], reset, nv)
        rp, mp = np.asarray(ref.packed), np.asarray(res.packed)
        np.testing.assert_array_equal(rp[valid, 0], mp[valid, 0])
        np.testing.assert_array_equal(rp[valid, 2], mp[valid, 2])
        # Scores: <= 2 ulp vs the scan on XLA:CPU — the scan and the
        # candidate replay are two differently FUSED compilations of the
        # same f32 ops (FMA contraction is per-fusion-shape), observed
        # as one score in ~100 off by 1 ulp with identical selections.
        # The BIT-exact bar is mesh-vs-single-device-keyed (same program
        # family), asserted in test_sharded_matches_single_device…
        np.testing.assert_array_almost_equal_nulp(
            np.where(np.isfinite(rp[valid, 1]), rp[valid, 1], 0.0),
            np.where(np.isfinite(mp[valid, 1]), mp[valid, 1], 0.0),
            nulp=2)
        np.testing.assert_array_equal(
            np.asarray(ref.usage_after), np.asarray(res.usage_after))

    def test_tie_breaks_to_lowest_global_row_across_shards(self):
        """Identical rows + zero noise: every placement ties across ALL
        shards, and the winner must be the lowest GLOBAL row — the
        single-device argmax rule — not a shard-local favorite. With the
        anti-affinity penalty, successive placements walk rows 0, 1, 2…
        in order, crossing shard boundaries (256 rows/shard)."""
        n, t, p = 2048, 1, 16
        d = dict(
            capacity=np.full((n, 5), 4000, np.float32),
            score_cap=np.full((n, 2), 3800, np.float32),
            usage=np.zeros((n, 5), np.float32),
            tg_masks=np.ones((t, n), bool),
            job_counts=np.zeros(n, np.int32),
            key_demands=np.full((t, 5), 10, np.float32),
            noise=np.zeros(n, np.float32),
            banned0=np.zeros(n, bool),
        )
        tg_ids = np.zeros(p, np.int32)
        valid = np.ones(p, bool)
        reset = np.zeros(p, bool)
        for mesh in (None, _mesh()):
            res = kernels.place_batch_keyed(
                mesh, d["capacity"], d["score_cap"], d["usage"],
                d["tg_masks"], d["job_counts"], d["key_demands"], tg_ids,
                valid, d["noise"], np.float32(10.0), np.asarray(False),
                d["banned0"], reset, p)
            chosen = np.asarray(res.packed)[:, 0].astype(int)
            np.testing.assert_array_equal(chosen, np.arange(p))

    def test_failed_placements_and_success_mask(self):
        """A key no node can fit: its placements report chosen=-1 /
        score=-inf identically on the scan, the single-device keyed
        kernel, and the mesh — and the compacted success mask is False
        for the eval containing them."""
        d = _inputs(n=1024, seed=23)
        d["key_demands"][1] = 1e9  # infeasible everywhere
        t = d["key_demands"].shape[0]
        p = 32
        tg_ids = (np.arange(p) % t).astype(np.int32)
        valid = np.ones(p, bool)
        reset = np.zeros(p, bool)
        demands = d["key_demands"][tg_ids]
        ref = kernels.place_batch(
            d["capacity"], d["score_cap"], d["usage"], d["tg_masks"],
            d["job_counts"], demands, tg_ids, valid, d["noise"],
            np.float32(10.0), np.asarray(False), d["banned0"])
        packs = [np.asarray(ref.packed)]
        for mesh in (None, _mesh()):
            res = kernels.place_batch_keyed(
                mesh, d["capacity"], d["score_cap"], d["usage"],
                d["tg_masks"], d["job_counts"], d["key_demands"], tg_ids,
                valid, d["noise"], np.float32(10.0), np.asarray(False),
                d["banned0"], reset, p)
            packs.append(np.asarray(res.packed))
        failed = tg_ids == 1
        for pk in packs:
            assert (pk[failed, 0] == -1).all()
            assert np.isneginf(pk[failed, 1]).all()
            assert not kernels.compact_host(pk, p).ok
        np.testing.assert_array_equal(packs[0][:, 0], packs[1][:, 0])
        np.testing.assert_array_equal(packs[1], packs[2])


class TestMeshServerFallbackParity:
    def test_forced_fallback_record_places_identically(self, monkeypatch):
        """Mesh-served stream with ONE forced plan-apply failure (a
        fallback record: the eval re-runs the exact path and the chain
        taints + rebases through the ChainArbiter) still commits the
        same placements as clean single-device serving."""
        from nomad_tpu import mock
        from nomad_tpu.resilience import failpoints
        from nomad_tpu.scheduler import stack as stack_mod
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs import compute_node_class
        from nomad_tpu.structs.structs import EvalStatusComplete

        from helpers import wait_for

        def fixed_noise(n_rows, rng):
            return np.asarray(
                np.random.default_rng(77).random(n_rows),
                dtype=np.float32) * 1e-3

        monkeypatch.setattr(stack_mod, "make_noise_vec", fixed_noise)

        nodes = []
        for i in range(32):
            node = mock.node()
            node.Meta["rack"] = f"r{i % 8}"
            node.Resources.CPU = 2000 + 400 * (i % 3)
            node.Resources.MemoryMB = 4096
            compute_node_class(node)
            nodes.append(node)

        def make_job():
            job = mock.job()
            tg = job.TaskGroups[0]
            tg.Count = 6
            task = tg.Tasks[0]
            task.Resources.CPU = 50
            task.Resources.MemoryMB = 64
            task.Resources.Networks = []
            task.Services = []
            return job

        jobs = [make_job() for _ in range(5)]
        results = []
        for mesh, inject in ((False, False), (True, True)):
            cfg = ServerConfig(num_schedulers=1, pipelined_scheduling=True,
                               scheduler_window=16,
                               scheduler_mesh="all" if mesh else "",
                               min_heartbeat_ttl=3600.0,
                               heartbeat_grace=3600.0)
            srv = Server(cfg)
            srv.establish_leadership()
            try:
                for node in pickle.loads(pickle.dumps(nodes)):
                    srv.node_register(node)
                placements = {}
                for j, job in enumerate(pickle.loads(pickle.dumps(jobs))):
                    if inject and j == 2:
                        # One commit failure mid-stream: the record goes
                        # fallback, the chain taints, the next window
                        # rebases through the arbiter.
                        failpoints.arm_from_spec(
                            "plan.apply.commit=error:count=1")
                    eval_id = srv.job_register(job)[0]
                    wait_for(
                        lambda: (e := srv.state.eval_by_id(eval_id))
                        is not None and e.Status == EvalStatusComplete,
                        timeout=60)
                    placements[j] = sorted(
                        a.NodeID for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status())
                if inject:
                    snap = failpoints.snapshot()
                    assert snap["plan.apply.commit"]["fired"] >= 1
                results.append(placements)
            finally:
                failpoints.disarm_all()
                srv.shutdown()
        single, sharded_with_fallback = results
        assert single == sharded_with_fallback
