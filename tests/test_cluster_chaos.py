"""Whole-cluster chaos test: a registration storm through a real networked
3-server cluster while the leader is killed and a survivor's gossip is
partitioned. The cross-subsystem composition the unit suites can't cover:
gossip bootstrap -> raft -> broker -> distributed workers -> plan applier
-> commit, under failover (reference composition: nomad/leader_test.go's
leader-loss suites run against C1M-style load).

Asserted invariants:
  - every evaluation reaches a terminal state (nothing lost in failover)
  - zero lost or duplicated allocations (exactly Count per job)
  - no node oversubscribed (token protocol + plan re-verification held)
  - throughput recovers: jobs submitted AFTER the kill also complete
"""

import os
import random
import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.gossip import GossipConfig
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.server.server import ServerConfig
from nomad_tpu.structs import to_dict
from nomad_tpu.structs.structs import (
    EvalStatusBlocked,
    EvalStatusCancelled,
    EvalStatusComplete,
    EvalStatusFailed,
)
from nomad_tpu.tensor.node_table import alloc_vec, resources_vec

from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked chaos suite: one retry

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout_min=0.08,
                  election_timeout_max=0.16, apply_timeout=5.0)

N_NODES = 80
N_JOBS = 90
PER_JOB = 3
KILL_AT = 30        # jobs submitted before the leader dies
PARTITION_AT = 60   # jobs submitted before a survivor's gossip partitions

TERMINAL = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)


def boot(name, join=None, expect=3, raft_config=None):
    cs = ClusterServer(ServerConfig(
        node_id="", num_schedulers=1, bootstrap_expect=expect,
        scheduler_window=8))
    cs.connect([], raft_config=raft_config or FAST)
    cs.start()
    cs.enable_gossip(name, join=join, gossip_config=GossipConfig.fast())
    return cs


def leader_of(nodes):
    for n in nodes:
        try:
            if n.server is not None and n.server.is_leader() \
                    and n.server._leader:
                return n
        except Exception:
            pass
    return None


def make_job():
    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = PER_JOB
    task = tg.Tasks[0]
    task.Resources.CPU = 20
    task.Resources.MemoryMB = 32
    task.Resources.Networks = []
    task.Services = []
    return job


class TestClusterChaos:
    def test_storm_survives_leader_kill_and_gossip_partition(self):
        nodes = [boot("s0")]
        nodes.append(boot("s1", join=[_gaddr(nodes[0])]))
        nodes.append(boot("s2", join=[_gaddr(nodes[0])]))
        live = list(nodes)
        try:
            assert wait_for(lambda: leader_of(live) is not None, timeout=30)

            # --- cluster inventory: mock nodes registered over RPC
            for _ in range(N_NODES):
                node = mock.node()
                _rpc_retry(live, "Node.Register", {"Node": to_dict(node)})

            jobs = [make_job() for _ in range(N_JOBS)]
            submitted = {}  # job_id -> eval_id (first successful register)
            errors = []

            def storm():
                for i, job in enumerate(jobs):
                    if i == KILL_AT:
                        kill_leader()
                    if i == PARTITION_AT:
                        partition_one()
                    try:
                        resp = _rpc_retry(live, "Job.Register",
                                          {"Job": to_dict(job)})
                        submitted[job.ID] = resp["EvalID"]
                    except Exception as e:  # total cluster loss: fail test
                        errors.append(e)
                        return
                    time.sleep(0.01)

            partitioned = []

            def kill_leader():
                victim = leader_of(live)
                if victim is not None:
                    live.remove(victim)
                    victim.shutdown()

            def partition_one():
                # A non-leader survivor loses its gossip links for a while
                # (raft RPC stays up: the quorum keeps committing).
                target = next((n for n in live
                               if n is not leader_of(live)), None)
                if target is None or target.membership is None:
                    return
                ml = target.membership.memberlist
                ml.transport_filter = lambda dest, msgs: False
                partitioned.append(ml)

            t = threading.Thread(target=storm)
            t.start()
            t.join(timeout=120)
            assert not t.is_alive(), "storm thread wedged"
            assert not errors, f"storm lost the cluster: {errors[0]}"
            assert len(submitted) == N_JOBS

            # Heal the partition; the member refutes its suspicion and
            # rejoins.
            for ml in partitioned:
                ml.transport_filter = None

            # --- every eval terminal on the current leader
            def all_terminal():
                ldr = leader_of(live)
                if ldr is None:
                    return False
                state = ldr.server.state
                for eval_id in submitted.values():
                    ev = state.eval_by_id(eval_id)
                    if ev is None or ev.Status not in TERMINAL:
                        return False
                return True

            assert wait_for(all_terminal, timeout=120, interval=0.25,
                            msg="all evals terminal after chaos")

            ldr = leader_of(live)
            state = ldr.server.state

            # --- zero lost or duplicated allocations
            for job in jobs:
                allocs = [a for a in state.allocs_by_job(job.ID)
                          if not a.terminal_status()]
                assert len(allocs) == PER_JOB, (
                    f"job {job.ID}: {len(allocs)} allocs, want {PER_JOB}")
                assert len({a.ID for a in allocs}) == len(allocs)

            # --- no node oversubscribed
            cap = {}
            for n in state.nodes():
                cap[n.ID] = resources_vec(n.Resources)
            used = {}
            for a in state.allocs():
                if a.terminal_status():
                    continue
                u = used.setdefault(a.NodeID,
                                    np.zeros(5, dtype=np.float64))
                u += alloc_vec(a)
            for nid, u in used.items():
                assert (u <= cap[nid] + 1e-6).all(), (
                    f"node {nid} oversubscribed: {u} > {cap[nid]}")

            # --- throughput recovered: the post-kill jobs all placed
            post_kill = jobs[KILL_AT:]
            assert all(
                len([a for a in state.allocs_by_job(j.ID)
                     if not a.terminal_status()]) == PER_JOB
                for j in post_kill)
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass


def _gaddr(cs):
    ml = cs.membership.memberlist
    return f"{ml.addr}:{ml.port}"


def _rpc_retry(live, method, args, attempts=40, delay=0.25):
    """Issue an RPC against any live server, retrying through elections
    and dead connections (what a real API client's retry loop does)."""
    last = None
    for _ in range(attempts):
        targets = [n for n in live if n.endpoints is not None]
        random.shuffle(targets)
        for cs in targets:
            try:
                return cs.endpoints.handle(method, dict(args))
            except Exception as e:
                last = e
        time.sleep(delay)
    raise last if last is not None else RuntimeError("no live servers")


@pytest.mark.skipif(not os.environ.get("NOMAD_TPU_SOAK"),
                    reason="set NOMAD_TPU_SOAK=1 for the extended soak")
class TestExtendedSoak:
    def test_sustained_storm_with_repeated_leader_kills(self):
        """Soak: a longer storm with TWO leader kills and a gossip
        partition; same invariants as the chaos test at 3x the load. Run
        with NOMAD_TPU_SOAK=1 (not part of the default CI budget).

        Raft timings are LOOSER than the quick chaos test's: four servers
        plus a sustained storm share one Python process here, and
        100ms-class election timeouts under that load produce perpetual
        leadership churn (a harness artifact, not a cluster property —
        real deployments run 150-500ms timeouts per the raft paper's
        guidance for their actual network, not their GIL)."""
        soak_raft = RaftConfig(heartbeat_interval=0.05,
                               election_timeout_min=0.30,
                               election_timeout_max=0.60,
                               apply_timeout=10.0)
        n_jobs = 240
        nodes = [boot("s0", raft_config=soak_raft)]
        nodes.append(boot("s1", join=[_gaddr(nodes[0])],
                          raft_config=soak_raft))
        nodes.append(boot("s2", join=[_gaddr(nodes[0])],
                          raft_config=soak_raft))
        nodes.append(boot("s3", join=[_gaddr(nodes[0])],
                          raft_config=soak_raft))
        live = list(nodes)
        try:
            assert wait_for(lambda: leader_of(live) is not None, timeout=30)
            for _ in range(N_NODES):
                _rpc_retry(live, "Node.Register",
                           {"Node": to_dict(mock.node())})
            jobs = [make_job() for _ in range(n_jobs)]
            submitted = {}
            partitioned = []

            def kill_leader():
                victim = leader_of(live)
                if victim is None or len(live) <= 2:
                    return
                live.remove(victim)
                victim.shutdown()
                # Rolling failures: the next kill must wait until gossip
                # failure detection has pruned this peer from the raft
                # config, or quorum would become unreachable — the same
                # operational constraint the reference has (you can't lose
                # 2 of 4 voters before reconciliation). Asserting the
                # prune IS part of the soak.
                assert wait_for(
                    lambda: (ldr := leader_of(live)) is not None
                    and victim.addr not in ldr.server.raft.peers,
                    timeout=30), "dead peer never pruned from raft config"

            for i, job in enumerate(jobs):
                if i in (60, 150):
                    kill_leader()
                if i == 100:
                    target = next((n for n in live
                                   if n is not leader_of(live)), None)
                    if target is not None and target.membership is not None:
                        ml = target.membership.memberlist
                        ml.transport_filter = lambda dest, msgs: False
                        partitioned.append(ml)
                if i == 200:
                    for ml in partitioned:
                        ml.transport_filter = None
                resp = _rpc_retry(live, "Job.Register",
                                  {"Job": to_dict(job)})
                submitted[job.ID] = resp["EvalID"]
                time.sleep(0.005)

            def all_terminal():
                ldr = leader_of(live)
                if ldr is None:
                    return False
                state = ldr.server.state
                return all(
                    (e := state.eval_by_id(eid)) is not None
                    and e.Status in TERMINAL
                    for eid in submitted.values())

            assert wait_for(all_terminal, timeout=180, interval=0.3)
            state = leader_of(live).server.state
            for job in jobs:
                allocs = [a for a in state.allocs_by_job(job.ID)
                          if not a.terminal_status()]
                assert len(allocs) == PER_JOB, (job.ID, len(allocs))
            cap = {n.ID: resources_vec(n.Resources) for n in state.nodes()}
            used = {}
            for a in state.allocs():
                if a.terminal_status():
                    continue
                u = used.setdefault(a.NodeID,
                                    np.zeros(5, dtype=np.float64))
                u += alloc_vec(a)
            for nid, u in used.items():
                assert (u <= cap[nid] + 1e-6).all()
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass
