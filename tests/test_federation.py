"""Federation unit tests: snapshot source, region forwarder, forward
dedupe, health view, broker floors, and the admission edge-shed
(ISSUE 14; the end-to-end gates live in test_federation_equivalence.py
and the chaos schedule in test_chaos_schedules.py)."""

import pytest

from nomad_tpu.federation import (
    FederationConfig,
    FederationHealth,
    ForwardDedup,
    NoRegionPathError,
    RegionForwarder,
    SnapshotSource,
)
from nomad_tpu.qos import AdmissionController, QoSConfig, QoSCounters
from nomad_tpu.qos.admission import QoSBackpressureError
from nomad_tpu.resilience import failpoints
from nomad_tpu.rpc.pool import ConnError, RPCError


class _FakeState:
    def __init__(self):
        self.index = 0
        self.snaps = 0

    def latest_index(self):
        return self.index

    def snapshot(self):
        self.snaps += 1

        class Snap:
            watermark = self.index
        return Snap()


class TestSnapshotSource:
    def _source(self, max_staleness=1.0):
        state = _FakeState()
        clock = {"t": 100.0}
        src = SnapshotSource(
            state, FederationConfig(enabled=True,
                                    max_staleness_s=max_staleness),
            clock=lambda: clock["t"])
        return state, clock, src

    def test_reuse_within_bound_refresh_past_it(self):
        state, clock, src = self._source(max_staleness=1.0)
        s1, born1 = src.get()
        s2, born2 = src.get()
        assert s1 is s2 and born1 == born2
        assert state.snaps == 1
        clock["t"] += 1.5  # past the bound
        s3, born3 = src.get()
        assert s3 is not s1 and born3 > born1
        assert state.snaps == 2
        assert src.stats()["Reused"] == 1
        assert src.stats()["Refreshed"] == 2

    def test_min_index_forces_refresh(self):
        state, clock, src = self._source()
        s1, _ = src.get()
        state.index = 7  # store moved past the cached watermark
        s2, _ = src.get(min_index=7)
        assert s2 is not s1 and s2.watermark == 7

    def test_pin_serves_stale_until_unpin(self):
        state, clock, src = self._source()
        pinned = state.snapshot()
        src.pin(pinned, born=clock["t"] - 50.0)
        s, born = src.get(min_index=10**9)  # pin wins over every bound
        assert s is pinned and born == clock["t"] - 50.0
        src.unpin()
        s2, _ = src.get()
        assert s2 is not pinned


class _FakePool:
    """pool.call stub: scripted per-addr behaviors."""

    def __init__(self, behaviors):
        self.behaviors = dict(behaviors)  # addr -> callable(method, body)
        self.calls = []

    def call(self, addr, method, body, timeout=None):
        self.calls.append((addr, method, dict(body)))
        return self.behaviors[addr](method, body)


class TestRegionForwarder:
    def test_retries_next_peer_on_transport_error(self):
        pool = _FakePool({
            "dead:1": lambda m, b: (_ for _ in ()).throw(
                ConnError("down")),
            "live:1": lambda m, b: {"ok": True},
        })
        fwd = RegionForwarder(pool, lambda r: ["dead:1", "live:1"],
                              fed=FederationConfig(enabled=True))
        assert fwd.forward("west", "Job.Register", {}) == {"ok": True}
        assert [a for a, _, _ in pool.calls] == ["dead:1", "live:1"]

    def test_single_peer_retried_on_transient_error(self):
        flaky = {"n": 0}

        def behave(m, b):
            flaky["n"] += 1
            if flaky["n"] == 1:
                raise ConnError("blip")
            return {"ok": flaky["n"]}

        pool = _FakePool({"only:1": behave})
        fwd = RegionForwarder(pool, lambda r: ["only:1"],
                              fed=FederationConfig(enabled=True))
        assert fwd.forward("west", "Job.Register", {})["ok"] == 2

    def test_forward_id_stamped_once_and_stable_across_retries(self):
        flaky = {"n": 0}

        def behave(m, b):
            flaky["n"] += 1
            if flaky["n"] == 1:
                raise ConnError("blip")
            return {}

        pool = _FakePool({"a:1": behave})
        fwd = RegionForwarder(pool, lambda r: ["a:1"],
                              fed=FederationConfig(enabled=True))
        fwd.forward("west", "Job.Register", {"Job": {}})
        ids = {b["ForwardID"] for _, _, b in pool.calls}
        assert len(ids) == 1 and ids != {None}
        # Reads are not stamped.
        pool2 = _FakePool({"a:1": lambda m, b: {}})
        fwd2 = RegionForwarder(pool2, lambda r: ["a:1"],
                               fed=FederationConfig(enabled=True))
        fwd2.forward("west", "Job.List", {})
        assert "ForwardID" not in pool2.calls[0][2]

    def test_remote_error_not_retried(self):
        pool = _FakePool({
            "a:1": lambda m, b: (_ for _ in ()).throw(
                RPCError("ValueError: bad job")),
        })
        fwd = RegionForwarder(pool, lambda r: ["a:1"],
                              fed=FederationConfig(enabled=True))
        with pytest.raises(RPCError):
            fwd.forward("west", "Job.Register", {})
        assert len(pool.calls) == 1  # the handler's answer IS the answer

    def test_breaker_quarantines_dead_peer(self):
        pool = _FakePool({
            "dead:1": lambda m, b: (_ for _ in ()).throw(
                ConnError("down")),
        })
        fed = FederationConfig(enabled=True, forward_attempts=2,
                               forward_breaker_threshold=2,
                               forward_breaker_reset_s=60.0)
        fwd = RegionForwarder(pool, lambda r: ["dead:1"], fed=fed)
        with pytest.raises(ConnError):
            fwd.forward("west", "Job.Register", {})
        assert fwd.breaker_state("dead:1") == "open"
        # Quarantined: the next forward fails FAST with a typed
        # no-path error instead of another connect timeout.
        before = len(pool.calls)
        with pytest.raises(NoRegionPathError):
            fwd.forward("west", "Job.Register", {})
        assert len(pool.calls) == before

    def test_no_peers_is_no_path(self):
        fwd = RegionForwarder(_FakePool({}), lambda r: [],
                              fed=FederationConfig(enabled=True))
        with pytest.raises(NoRegionPathError):
            fwd.forward("nowhere", "Job.Register", {})

    def test_drop_failpoint_delivers_then_retries(self):
        """drop = the ambiguous failure: the request REACHES the region
        (the call happens) but the response is lost; the retry replays
        the same ForwardID."""
        pool = _FakePool({"a:1": lambda m, b: {}})
        fwd = RegionForwarder(pool, lambda r: ["a:1"],
                              fed=FederationConfig(enabled=True))
        failpoints.disarm_all()
        try:
            failpoints.arm("rpc.forward_region", "drop", count=1)
            fwd.forward("west", "Job.Register", {"Job": {}})
            assert len(pool.calls) == 2  # delivered twice...
            assert pool.calls[0][2]["ForwardID"] \
                == pool.calls[1][2]["ForwardID"]  # ...same identity
        finally:
            failpoints.disarm_all()


class TestForwardDedup:
    def test_replay_answers_from_cache(self):
        d = ForwardDedup()
        hit, _ = d.get("id-1")
        assert not hit
        d.put("id-1", {"EvalID": "e1"})
        hit, resp = d.get("id-1")
        assert hit and resp == {"EvalID": "e1"}

    def test_lru_bound(self):
        d = ForwardDedup(cap=2)
        d.put("a", 1)
        d.put("b", 2)
        d.put("c", 3)
        assert not d.get("a")[0]
        assert d.get("b")[0] and d.get("c")[0]

    def test_replay_during_execution_parks_until_put(self):
        """The ambiguous-WAN race: a replay arriving while the ORIGINAL
        delivery is still executing must wait for its answer, never
        start a second concurrent execution."""
        import threading

        d = ForwardDedup()
        hit, _ = d.begin("id-1")
        assert not hit  # reserved by the "original delivery"
        got = {}

        def replay():
            got["result"] = d.begin("id-1", timeout=10.0)

        t = threading.Thread(target=replay)
        t.start()
        t.join(timeout=0.2)
        assert t.is_alive(), "replay must park on the reservation"
        d.put("id-1", {"EvalID": "e1"})
        t.join(timeout=10)
        assert not t.is_alive()
        assert got["result"] == (True, {"EvalID": "e1"})

    def test_abort_lets_replay_reexecute(self):
        """A handler that raised committed nothing: the parked replay
        takes over the reservation (miss) and re-executes."""
        import threading

        d = ForwardDedup()
        assert d.begin("id-1") == (False, None)
        got = {}

        def replay():
            got["result"] = d.begin("id-1", timeout=10.0)

        t = threading.Thread(target=replay)
        t.start()
        t.join(timeout=0.2)
        assert t.is_alive()
        d.abort("id-1")
        t.join(timeout=10)
        assert not t.is_alive()
        assert got["result"] == (False, None)  # replay now owns the id
        d.put("id-1", "second-try")
        assert d.get("id-1") == (True, "second-try")


class TestFederationHealth:
    def _view(self, ttl=10.0):
        clock = {"t": 0.0}
        fed = FederationConfig(enabled=True, health_ttl_s=ttl)
        return clock, FederationHealth(fed, clock=lambda: clock["t"])

    def test_shedding_on_remote_depth(self):
        clock, h = self._view()
        h.update("west", {"TierDepths": [0, 0, 5000],
                          "SLOBurn": [0.0, 0.0, 0.0],
                          "AdmitDepth": [0, 8192, 2048],
                          "BurnShed": 0.5})
        assert h.region_shedding("west", 2) is not None
        assert h.region_shedding("west", 0) is None

    def test_shedding_on_remote_burn(self):
        clock, h = self._view()
        h.update("west", {"TierDepths": [3, 0, 0],
                          "SLOBurn": [0.9, 0.0, 0.0],
                          "AdmitDepth": [0, 8192, 2048],
                          "BurnShed": 0.5})
        assert h.region_shedding("west", 2) is not None  # high burning
        assert h.region_shedding("west", 0) is None

    def test_stale_entry_assumed_healthy(self):
        clock, h = self._view(ttl=5.0)
        h.update("west", {"TierDepths": [0, 0, 5000],
                          "SLOBurn": [0, 0, 0],
                          "AdmitDepth": [0, 0, 1],
                          "BurnShed": 0.5})
        clock["t"] += 6.0
        assert h.get("west") is None
        assert h.region_shedding("west", 2) is None


class _FakeBroker:
    def __init__(self):
        self.depths = [0, 0, 0]
        self.burn = [0.0, 0.0, 0.0]

    def tier_depths(self):
        return list(self.depths)

    def slo_burn(self):
        return list(self.burn)


class TestAdmitForward:
    def test_sheds_on_remote_health(self):
        fed = FederationConfig(enabled=True)
        health = FederationHealth(fed)
        health.update("west", {"TierDepths": [0, 0, 5000],
                               "SLOBurn": [0, 0, 0],
                               "AdmitDepth": [0, 8192, 2048],
                               "BurnShed": 0.5})
        counters = QoSCounters()
        adm = AdmissionController(QoSConfig(enabled=True), _FakeBroker(),
                                  counters, fed=fed, fed_health=health)
        with pytest.raises(QoSBackpressureError):
            adm.admit_forward("west", 10)  # low tier, remote backlog
        adm.admit_forward("west", 90)      # high tier passes
        assert counters.snapshot()["forward_shed"] == 1

    def test_noop_without_federation(self):
        adm = AdmissionController(QoSConfig(enabled=True), _FakeBroker(),
                                  QoSCounters())
        adm.admit_forward("west", 10)  # never raises


class TestRegionStampEndToEnd:
    """ISSUE 14 satellite: a job forwarded to its home region keeps
    Region stamped consistently on the job, its evals, and its allocs
    end to end — and the forward triggers on job.Region ALONE (no
    Region query param), the ingress hole the one-helper
    ``_default_region`` dedupe closes."""

    def test_forwarded_job_keeps_region_on_job_evals_allocs(self):
        from helpers import wait_for

        from nomad_tpu import mock
        from nomad_tpu.gossip import GossipConfig
        from nomad_tpu.raft import RaftConfig
        from nomad_tpu.rpc.cluster import ClusterServer
        from nomad_tpu.server.server import ServerConfig
        from nomad_tpu.structs import to_dict
        from nomad_tpu.structs.structs import EvalStatusComplete

        fast = RaftConfig(heartbeat_interval=0.02,
                          election_timeout_min=0.08,
                          election_timeout_max=0.16, apply_timeout=5.0)

        def boot(name, region, join=None):
            cs = ClusterServer(ServerConfig(
                node_id="", region=region, num_schedulers=1,
                scheduler_window=8, bootstrap_expect=1,
                federation=FederationConfig(enabled=True)))
            cs.connect([], raft_config=fast)
            cs.start()
            cs.enable_gossip(name, join=join,
                             gossip_config=GossipConfig.fast())
            return cs

        a = boot("ra0", "alpha")
        b = None
        try:
            assert wait_for(lambda: a.server.is_leader(), timeout=15)
            b = boot("rb0", "beta",
                     join=[f"{a.membership.memberlist.addr}:"
                           f"{a.membership.memberlist.port}"])
            assert wait_for(lambda: b.server.is_leader(), timeout=15)
            assert wait_for(
                lambda: b.membership.region_servers("alpha"), timeout=15)
            for _ in range(3):
                a.endpoints.handle("Node.Register",
                                   {"Node": to_dict(mock.node())})
            job = mock.job()
            job.Region = "alpha"
            job.TaskGroups[0].Count = 3
            task = job.TaskGroups[0].Tasks[0]
            task.Resources.CPU = 20
            task.Resources.MemoryMB = 32
            task.Resources.Networks = []
            task.Services = []
            if task.LogConfig is not None:
                task.LogConfig.MaxFiles = 1
                task.LogConfig.MaxFileSizeMB = 1
            # NOTE: no Region query param — the forward keys off
            # job.Region at ingress, before any raft write.
            resp = b.endpoints.handle("Job.Register",
                                      {"Job": to_dict(job)})
            eid = resp["EvalID"]
            state = a.server.state
            stored = state.job_by_id(job.ID)
            assert stored is not None and stored.Region == "alpha"
            assert b.server.state.job_by_id(job.ID) is None
            ev = state.eval_by_id(eid)
            assert ev is not None and ev.Region == "alpha"
            assert wait_for(
                lambda: (e := state.eval_by_id(eid)) is not None
                and e.Status == EvalStatusComplete, timeout=30)
            allocs = state.allocs_by_job(job.ID)
            assert len(allocs) == 3
            for alloc in allocs:
                assert alloc.Job is not None \
                    and alloc.Job.Region == "alpha"
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()
