"""QoS alloc preemption (ISSUE 8): victim selection/ranking, the plan
applier's atomic evict+place guarantee, the two-submitter overlap race,
and the plan.preempt.commit chaos schedule (a worker killed mid-preemption
redelivers exactly once — no lost evictions, no duplicate allocs)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.qos import QoSConfig, find_preemption
from nomad_tpu.resilience import failpoints
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import FSM, DevRaft, MessageType
from nomad_tpu.server.plan_apply import PlanApplier, evaluate_plan
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import Plan, compute_node_class
from nomad_tpu.structs.structs import (
    AllocDesiredStatusEvict,
    AllocDesiredStatusRun,
    EvalStatusComplete,
)

from helpers import wait_for  # noqa: E402


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


def _node(raft, cpu=1000):
    node = mock.node()
    node.Resources.CPU = cpu
    node.Reserved = None
    compute_node_class(node)
    raft.apply(MessageType.NodeRegister, {"Node": node})
    return node


def _victim(raft, node, job, cpu):
    """A committed low-priority alloc occupying `cpu` on `node`."""
    alloc = mock.alloc()
    alloc.NodeID = node.ID
    alloc.JobID = job.ID
    alloc.Job = None
    alloc.Resources.CPU = cpu
    alloc.Resources.Networks = []
    alloc.TaskResources = {}
    raft.apply(MessageType.AllocUpdate, {"Alloc": [alloc], "Job": job})
    return raft.fsm.state.alloc_by_id(alloc.ID)


def _register_job(raft, priority):
    job = mock.job()
    job.Priority = priority
    raft.apply(MessageType.JobRegister, {"Job": job})
    return raft.fsm.state.job_by_id(job.ID)


def _high_tg(cpu):
    job = mock.job()
    job.Priority = 90
    tg = job.TaskGroups[0]
    task = tg.Tasks[0]
    task.Resources.CPU = cpu
    task.Resources.MemoryMB = 0
    task.Resources.DiskMB = 0
    task.Resources.IOPS = 0
    task.Resources.Networks = []
    return job, tg


class TestFindPreemption:
    def test_ranks_lowest_priority_youngest_first(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        mid = _register_job(raft, 50)
        v_old = _victim(raft, node, low, 300)
        v_young = _victim(raft, node, low, 300)
        v_mid = _victim(raft, node, mid, 300)
        job, tg = _high_tg(250)
        qos = QoSConfig(enabled=True)
        pick = find_preemption(fsm.state.snapshot(), Plan(), job, tg,
                               [node], qos)
        assert pick is not None
        # One eviction suffices; lowest priority + youngest wins.
        assert [v.ID for v in pick.victims] == [v_young.ID]
        assert v_old.ID != v_young.ID and v_mid.ID not in {v_young.ID}

    def test_never_evicts_equal_or_higher_tier(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        high_job = _register_job(raft, 90)
        normal_job = _register_job(raft, 50)
        _victim(raft, node, high_job, 500)
        _victim(raft, node, normal_job, 400)
        job, tg = _high_tg(600)
        qos = QoSConfig(enabled=True)
        pick = find_preemption(fsm.state.snapshot(), Plan(), job, tg,
                               [node], qos)
        # Evicting the normal-tier 400 leaves 500 high-tier in place:
        # 500 + 600 > 1000, and the high-tier alloc is untouchable.
        assert pick is None

    def test_max_victims_bounds_blast_radius(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        for _ in range(5):
            _victim(raft, node, low, 190)
        job, tg = _high_tg(800)  # needs 4+ evictions
        pick = find_preemption(fsm.state.snapshot(), Plan(), job, tg,
                               [node], QoSConfig(enabled=True,
                                                 max_victims=2))
        assert pick is None
        pick = find_preemption(fsm.state.snapshot(), Plan(), job, tg,
                               [node], QoSConfig(enabled=True,
                                                 max_victims=5))
        assert pick is not None and len(pick.victims) == 4

    def test_network_asks_never_preempt(self):
        from nomad_tpu.structs import NetworkResource
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        _victim(raft, node, low, 900)
        job, tg = _high_tg(500)
        tg.Tasks[0].Resources.Networks = [
            NetworkResource(MBits=10, DynamicPorts=["http"])]
        pick = find_preemption(fsm.state.snapshot(), Plan(), job, tg,
                               [node], QoSConfig(enabled=True))
        assert pick is None

    def test_sibling_instances_never_double_book_one_node(self):
        """Review regression: a Count>=2 high-tier job whose instances
        each need a preemption must spread across nodes — without
        pending-placement accounting both instances 'find' the same
        node's freed capacity, the applier bounces it every retry, and
        the eval fails although a one-victim-per-node plan exists."""
        from nomad_tpu.qos import attempt_preemption
        fsm = FSM()
        raft = DevRaft(fsm)
        node_a = _node(raft, cpu=1000)
        node_b = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        for node in (node_a, node_b):
            _victim(raft, node, low, 400)
            _victim(raft, node, low, 400)
        job, tg = _high_tg(600)
        plan = Plan(EvalID="ev-sibling", Priority=90)

        class _Tup:
            TaskGroup = tg

        options = attempt_preemption(
            fsm.state.snapshot(), plan, "ev-sibling", job,
            [_Tup(), _Tup()], [None, None], [node_a, node_b],
            QoSConfig(enabled=True))
        assert all(o is not None for o in options), options
        chosen = {o.node.ID for o in options}
        assert chosen == {node_a.ID, node_b.ID}, \
            "both instances double-booked one node"
        # And the combined plan verifies cleanly — nothing bounces.
        for tup, o in zip([_Tup(), _Tup()], options):
            placed = mock.alloc()
            placed.NodeID = o.node.ID
            placed.Resources.CPU = 600
            placed.Resources.Networks = []
            placed.TaskResources = {}
            plan.append_alloc(placed)
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert len(result.NodeAllocation) == 2
        assert result.RefreshIndex == 0  # full commit, no partial

    def test_accounts_in_plan_placements_and_evictions(self):
        # A plan that already placed 500 on the node leaves no room even
        # after evicting the victim: find_preemption must see it.
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        _victim(raft, node, low, 600)
        job, tg = _high_tg(600)
        plan = Plan()
        planned = mock.alloc()
        planned.NodeID = node.ID
        planned.Resources.CPU = 500
        planned.Resources.Networks = []
        planned.TaskResources = {}
        planned.JobID = job.ID
        plan.append_alloc(planned)
        pick = find_preemption(fsm.state.snapshot(), plan, job, tg,
                               [node], QoSConfig(enabled=True))
        assert pick is None  # 500 (in-plan) + 600 (ask) > 1000 even evicted


class TestApplierAtomicity:
    """Never an eviction without its placement committing."""

    def _preempt_plan(self, node, victim, cpu, include_placement=True):
        plan = Plan(EvalID=f"ev-{time.monotonic_ns()}", Priority=90)
        plan.append_update(victim, AllocDesiredStatusEvict, "preempted")
        placed = None
        if include_placement:
            placed = mock.alloc()
            placed.NodeID = node.ID
            placed.Resources.CPU = cpu
            placed.Resources.Networks = []
            placed.TaskResources = {}
            plan.append_alloc(placed)
        plan._preempt = {node.ID: [victim.ID]}
        return plan, placed

    def test_placement_unfit_drops_evictions_too(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        victim = _victim(raft, node, low, 300)
        _victim(raft, node, low, 600)
        # Evicting the 300 leaves 600: a 900 ask still cannot fit.
        plan, _ = self._preempt_plan(node, victim, cpu=900)
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert result.NodeAllocation == {} and result.NodeUpdate == {}
        assert result.RefreshIndex > 0  # partial verdict, worker re-plans

    def test_malformed_eviction_only_preempt_plan_drops(self):
        # Without the guard this rides "evict-only always fits" and stops
        # a victim for a placement that never existed.
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        victim = _victim(raft, node, low, 300)
        plan, _ = self._preempt_plan(node, victim, cpu=0,
                                     include_placement=False)
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert result.NodeUpdate == {}, \
            "eviction committed without its placement"

    def test_commit_counters_exclude_normal_placements_on_same_node(self):
        # Review regression: a preempting node may also carry the plan's
        # NORMALLY-selected placements; preempt_placed must count only
        # the instances that landed via preemption.
        from nomad_tpu.qos import QoSCounters
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.plan_queue import PlanQueue
        from nomad_tpu.structs import PlanResult

        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        victim = _victim(raft, node, low, 300)
        plan, placed = self._preempt_plan(node, victim, cpu=200)
        normal = mock.alloc()
        normal.NodeID = node.ID
        normal.Resources.CPU = 200
        normal.Resources.Networks = []
        normal.TaskResources = {}
        plan.append_alloc(normal)  # same node, NOT via preemption
        plan._preempt_counts = {node.ID: 1}
        counters = QoSCounters()
        applier = PlanApplier(PlanQueue(), raft, qos_counters=counters)
        result = PlanResult(
            NodeUpdate={node.ID: [victim]},
            NodeAllocation={node.ID: [placed, normal]})
        applier._count_preempt(plan, result)
        snap = counters.snapshot()
        assert snap["preempt_placed"] == 1, snap
        assert snap["preempt_evictions"] == 1, snap

    def test_fit_preemption_commits_both_sides(self):
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        victim = _victim(raft, node, low, 800)
        plan, placed = self._preempt_plan(node, victim, cpu=600)
        result = evaluate_plan(fsm.state.snapshot(), plan)
        assert [a.ID for a in result.NodeUpdate[node.ID]] == [victim.ID]
        assert [a.ID for a in result.NodeAllocation[node.ID]] == [placed.ID]

    def test_two_submitter_overlap_never_double_spends_eviction(self):
        """Two workers race preemption plans against the SAME victim: at
        most one placement commits; the victim is evicted exactly once;
        the loser gets a partial verdict (re-plan), never a phantom
        eviction credit."""
        fsm = FSM()
        raft = DevRaft(fsm)
        node = _node(raft, cpu=1000)
        low = _register_job(raft, 10)
        victim = _victim(raft, node, low, 800)

        broker = EvalBroker()  # disabled: applier skips the token check
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        applier.start()
        try:
            plan_a, placed_a = self._preempt_plan(node, victim, cpu=600)
            plan_b, placed_b = self._preempt_plan(node, victim, cpu=600)
            pendings = queue.enqueue_all([plan_a, plan_b])
            results = [p.wait(timeout=30.0) for p in pendings]
        finally:
            applier.stop()
            applier.join()
            queue.set_enabled(False)

        committed = [r for r in results if r.NodeAllocation]
        assert len(committed) == 1, results
        # Exactly one eviction of the victim landed.
        evictions = [a for r in results
                     for allocs in r.NodeUpdate.values() for a in allocs]
        assert [a.ID for a in evictions] == [victim.ID]
        state_victim = fsm.state.alloc_by_id(victim.ID)
        assert state_victim.DesiredStatus == AllocDesiredStatusEvict
        live = [a for a in fsm.state.allocs_by_node_terminal(node.ID, False)]
        assert len(live) == 1 and live[0].ID in {placed_a.ID, placed_b.ID}


def _slo_server(**qos_kw):
    srv = Server(ServerConfig(num_schedulers=1,
                              qos=QoSConfig(enabled=True, **qos_kw),
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    return srv


def _fat_job(prio, cpu):
    job = mock.job()
    job.Priority = prio
    tg = job.TaskGroups[0]
    tg.Count = 1
    task = tg.Tasks[0]
    task.Resources.CPU = cpu
    task.Resources.MemoryMB = 32
    task.Resources.DiskMB = 10
    task.Resources.Networks = []
    task.Services = []
    if task.LogConfig is not None:
        task.LogConfig.MaxFiles = 1
        task.LogConfig.MaxFileSizeMB = 1
    return job


def _wait_complete(srv, eid, timeout=30):
    assert wait_for(
        lambda: (e := srv.state.eval_by_id(eid)) is not None
        and e.Status == EvalStatusComplete,
        timeout=timeout, interval=0.02,
        msg=f"eval {eid} complete")
    return srv.state.eval_by_id(eid)


class TestPreemptionServed:
    """End-to-end through the live served path (register -> broker ->
    pipelined worker -> preemption fallback -> plan apply -> commit)."""

    def _saturate(self, srv, n_nodes=2):
        for _ in range(n_nodes):
            node = mock.node()
            node.Resources.CPU = 1000
            node.Reserved = None
            compute_node_class(node)
            srv.node_register(node)
        for _ in range(n_nodes):
            _wait_complete(srv, srv.job_register(_fat_job(10, 800))[0])

    def test_high_tier_preempts_through_served_path(self):
        srv = _slo_server()
        try:
            self._saturate(srv)
            heid = srv.job_register(_fat_job(90, 600))[0]
            _wait_complete(srv, heid)
            allocs = list(srv.state.allocs_by_eval(heid))
            assert len(allocs) == 1
            assert allocs[0].DesiredStatus == AllocDesiredStatusRun
            evicted = [a for a in srv.state.allocs()
                       if a.DesiredStatus == AllocDesiredStatusEvict]
            assert len(evicted) == 1
            snap = srv.qos_counters.snapshot()
            assert snap["preempt_placed"] == 1
            assert snap["preempt_evictions"] == 1
        finally:
            srv.shutdown()

    def test_low_tier_blocks_instead_of_preempting(self):
        srv = _slo_server()
        try:
            self._saturate(srv)
            # A NORMAL-tier job that doesn't fit must take the classic
            # blocked-eval path — no evictions.
            beid = srv.job_register(_fat_job(50, 600))[0]
            assert wait_for(
                lambda: (e := srv.state.eval_by_id(beid)) is not None
                and e.Status in ("complete", "blocked"),
                timeout=30, interval=0.02)
            evicted = [a for a in srv.state.allocs()
                       if a.DesiredStatus == AllocDesiredStatusEvict]
            assert evicted == []
            assert srv.qos_counters.snapshot()["preempt_placed"] == 0
        finally:
            srv.shutdown()

    def test_preempt_commit_killed_redelivers_exactly_once(self):
        """Chaos (ISSUE 8 satellite): the consensus commit of the
        preemption dies once; the worker nacks, the broker redelivers,
        and the retry commits evictions + placement together — exactly
        one high alloc, no eviction without it, no duplicates."""
        srv = _slo_server()
        try:
            self._saturate(srv)
            failpoints.arm_from_spec("plan.preempt.commit=error:count=1")
            heid = srv.job_register(_fat_job(90, 600))[0]
            _wait_complete(srv, heid, timeout=60)
            snap = failpoints.snapshot()
            assert snap["plan.preempt.commit"]["fired"] >= 1, \
                "chaos never hit the preempt commit seam"
            allocs = list(srv.state.allocs_by_eval(heid))
            assert len(allocs) == 1, "duplicate or lost high-tier alloc"
            evicted = [a for a in srv.state.allocs()
                       if a.DesiredStatus == AllocDesiredStatusEvict]
            # Every committed eviction has the committed placement it
            # paid for; capacity is never exceeded by survivors.
            assert len(evicted) >= 1
            for node_id in {a.NodeID for a in srv.state.allocs()}:
                live = srv.state.allocs_by_node_terminal(node_id, False)
                assert sum(a.Resources.CPU for a in live
                           if a.Resources) <= 1000
        finally:
            srv.shutdown()

    def test_admission_failpoint_served_path(self):
        from nomad_tpu.qos import QoSBackpressureError
        srv = _slo_server()
        try:
            node = mock.node()
            compute_node_class(node)
            srv.node_register(node)
            failpoints.arm_from_spec("broker.admission=drop:count=1")
            with pytest.raises(QoSBackpressureError):
                srv.job_register(_fat_job(10, 20))
            # Healed: same submission now lands.
            _wait_complete(srv, srv.job_register(_fat_job(10, 20))[0])
        finally:
            srv.shutdown()
