"""Tier-1 gate for the static analysis framework (nomad_tpu/analysis):

* the whole package tree is lint-clean — every checker, zero unsuppressed
  findings (the Python analogue of the reference's `go vet` CI step);
* every checker FIRES on the seeded-violation fixture, so a checker that
  silently stops matching can't keep the gate green;
* the `# lint: allow(<checker>, <reason>)` suppression grammar works and
  demands a reason;
* the telemetry-key checks migrated from tests/test_telemetry_lint.py
  (failpoint registry round-trip, nomad.* metric keys, span-name scheme)
  keep their assertions through the framework;
* `nomad-tpu lint` exits 0 on the tree and nonzero on the fixture.
"""

import os
import textwrap

import pytest

from nomad_tpu.analysis import all_checkers, run_checks
from nomad_tpu.cli.commands import main as cli_main

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "lint_violations.py")

EXPECTED_CHECKERS = {"guarded_by", "lock_blocking", "retry", "thread",
                     "swallow", "failpoint_site", "metric_key", "trace_key",
                     "event_schema", "apply_pure"}


def test_framework_hosts_the_expected_checkers():
    ids = {c.id for c in all_checkers()}
    assert EXPECTED_CHECKERS <= ids


def test_tree_is_lint_clean():
    findings = run_checks()
    assert not findings, "unsuppressed lint findings:\n" + "\n".join(
        f.render() for f in findings)


@pytest.mark.parametrize("checker", sorted(EXPECTED_CHECKERS))
def test_every_checker_fires_on_the_fixture(checker):
    findings = run_checks(paths=[FIXTURE], checker_ids=[checker])
    assert findings, f"checker {checker!r} found nothing in the fixture"
    assert all(f.checker == checker for f in findings)
    assert all(f.path == FIXTURE and f.line > 0 for f in findings)


def test_thread_checker_distinguishes_unnamed_and_untracked():
    messages = [f.message for f in
                run_checks(paths=[FIXTURE], checker_ids=["thread"])]
    assert any("without name=" in m for m in messages)
    assert any("no retained handle" in m for m in messages)


# ----------------------------------------------------------- suppressions
def _write(tmp_path, body):
    p = tmp_path / "case.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_suppression_comment_silences_a_finding(tmp_path):
    path = _write(tmp_path, """\
        def f():
            try:
                pass
            # lint: allow(swallow, fixture demonstrates suppression)
            except Exception:
                pass
    """)
    assert run_checks(paths=[path], checker_ids=["swallow"]) == []
    suppressed = run_checks(paths=[path], checker_ids=["swallow"],
                            include_suppressed=True)
    assert len(suppressed) == 1 and suppressed[0].suppressed


def test_suppression_requires_matching_checker_id(tmp_path):
    path = _write(tmp_path, """\
        def f():
            try:
                pass
            # lint: allow(retry, wrong checker id on purpose)
            except Exception:
                pass
    """)
    assert len(run_checks(paths=[path], checker_ids=["swallow"])) == 1


def test_suppression_without_reason_does_not_parse(tmp_path):
    path = _write(tmp_path, """\
        def f():
            try:
                pass
            # lint: allow(swallow)
            except Exception:
                pass
    """)
    assert len(run_checks(paths=[path], checker_ids=["swallow"])) == 1


def test_retry_checker_reports_nested_loop_sleep_once(tmp_path):
    path = _write(tmp_path, """\
        import time

        def f(items):
            while True:
                for _ in items:
                    time.sleep(1)
    """)
    assert len(run_checks(paths=[path], checker_ids=["retry"])) == 1


def test_suppression_on_the_same_line(tmp_path):
    path = _write(tmp_path, """\
        import time

        def f():
            while True:
                time.sleep(1)  # lint: allow(retry, demo same-line allow)
    """)
    assert run_checks(paths=[path], checker_ids=["retry"]) == []


# ------------------------------------- migrated telemetry-key assertions
def test_fired_failpoint_sites_match_known_sites():
    """Same assertion test_telemetry_lint.py made: full-tree scans prove
    fire() literals and KNOWN_SITES agree in BOTH directions."""
    assert run_checks(checker_ids=["failpoint_site"]) == []


def test_metric_and_trace_key_literals_follow_the_schemes():
    assert run_checks(checker_ids=["metric_key", "trace_key"]) == []


def test_event_literals_match_the_schema_registry():
    """Every new_event() topic/type literal in the tree (builders,
    broker fan-out) exists in events.schema and agrees topic-to-type."""
    assert run_checks(checker_ids=["event_schema"]) == []


def test_unknown_checker_id_is_an_error():
    with pytest.raises(ValueError):
        run_checks(checker_ids=["no_such_checker"])


# ------------------------------------------------------------------- CLI
def test_cli_lint_clean_tree_exits_zero(capsys):
    assert cli_main(["lint"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_lint_fixture_exits_nonzero(capsys):
    assert cli_main(["lint", FIXTURE]) == 1
    out = capsys.readouterr().out
    for checker in EXPECTED_CHECKERS:
        assert f"[{checker}]" in out, f"no {checker} finding in CLI output"


def test_cli_lint_json_output(capsys):
    import json

    assert cli_main(["lint", "-json", FIXTURE]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["total"] == len(payload["findings"]) > 0
    sample = payload["findings"][0]
    assert {"checker", "path", "line", "message"} <= set(sample)


def test_cli_lint_unknown_checker_exits_two(capsys):
    assert cli_main(["lint", "-checker", "bogus"]) == 2
    assert "known checkers" in capsys.readouterr().err


def test_cli_lint_suppressions_audit(capsys):
    """`lint -suppressions` is the purity-boundary ledger: every active
    allow() with file:line, checker id, and reason; always exit 0."""
    import json

    assert cli_main(["lint", "-suppressions"]) == 0
    out = capsys.readouterr().out
    # The apply-path allows annotated for the purity checker are listed
    # with their reasons (the auditable part).
    assert "allow(apply_pure)" in out
    assert "suppression(s)" in out

    assert cli_main(["lint", "-suppressions", "-json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["total"] == len(payload["suppressions"]) > 0
    sample = payload["suppressions"][0]
    assert {"File", "Line", "Checker", "Reason"} <= set(sample)
    assert all(r["Reason"] for r in payload["suppressions"])

    # -checker narrows the audit the same way it narrows a lint run.
    assert cli_main(["lint", "-suppressions", "-checker",
                     "apply_pure"]) == 0
    out = capsys.readouterr().out
    assert "allow(apply_pure)" in out and "allow(swallow)" not in out


def test_per_file_cache_serves_repeat_runs():
    from nomad_tpu.analysis import framework

    framework.load_file(FIXTURE)
    before = framework._CACHE[os.path.abspath(FIXTURE)]
    framework.load_file(FIXTURE)
    assert framework._CACHE[os.path.abspath(FIXTURE)][2] is before[2]
