"""bench.py --smoke: the in-tree perf-path regression guard.

Runs the REAL benchmark entry point (subprocess, same interpreter) at its
tiny CPU-safe shapes and asserts it completes with the placement-parity
quality gate green. Slow-marked: it is a multi-second end-to-end run, so
tier-1 (`-m 'not slow'`) skips it while `pytest -m slow` and soak sweeps
exercise it.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_completes_with_parity():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=300, cwd=ROOT, env=env)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    # The bench prints ONE json line (plus whatever libraries chatter).
    line = next(ln for ln in reversed(proc.stdout.strip().splitlines())
                if ln.startswith("{"))
    result = json.loads(line)
    assert result["value"] > 0
    detail = result["detail"]
    assert detail["placement_parity"]["ok"] is True
    stats = detail["e2e_worker_stats"]
    # The fast path actually ran, and the declared stats schema is intact.
    assert stats["fast"] > 0
    for key in ("t_dispatch_ms", "t_collect_ms", "t_drain_fetch_ms",
                "t_build_ms", "t_planwait_ms", "t_lease_ms"):
        assert key in stats
    # The system-sweep config runs at smoke scale too (ISSUE 6): the
    # tensor-sweep path must place one alloc per node per eval, so a
    # system-path regression surfaces in every smoke JSON.
    c4 = detail["config4_system"]
    assert c4["evals_sec"] > 0
    assert c4["placed_per_rep"] == c4["nodes"] * 4, c4
    # The worker-scaling sweep ran and recorded the 1-vs-2 ratio: two
    # workers must not COLLAPSE against one. The pre-arbiter state was
    # ~0.2x and parity-or-better is the expectation (measured ~0.96-1.13
    # on this box); the 0.6 floor is what separates "collapse regression"
    # from a CPU-throttling phase poisoning one side's short reps.
    scaling = detail["worker_scaling"]
    for key in ("workers_1", "workers_2", "ratio"):
        assert key in scaling
    assert scaling["workers_1"] > 0 and scaling["workers_2"] > 0
    assert scaling["ratio"] >= 0.6, scaling
    # The QoS slo_storm ran parity-gated (ISSUE 8): both modes placed the
    # full mixed-priority storm, per-tier percentiles recorded, and the
    # deterministic admission/preemption probes shed and preempted.
    slo = detail["slo_storm"]
    assert slo["parity_ok"] is True, slo
    assert slo["admission_probe"]["ok"] is True, slo
    assert slo["preempt_probe"]["ok"] is True, slo
    for mode in ("qos_off", "qos_on"):
        assert slo[mode]["high_ms"].get("p99", 0) > 0, slo
