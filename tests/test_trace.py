"""Evaluation-lifecycle tracing tests (telemetry/trace.py): span model +
carrier propagation unit tests, and the end-to-end acceptance drive — one
job register through a dev agent to a running task, asserting a single
connected trace across server- and client-side work, retrievable through
/v1/agent/debug/trace and exportable as Chrome trace-event JSON."""

import json
import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import Client as APIClient
from nomad_tpu.jobspec import parse_job
from nomad_tpu.telemetry import trace

from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry


@pytest.fixture(autouse=True)
def _trace_reset():
    """Every test starts disarmed with an empty collector and leaves the
    global tracer the way tier-1 expects it: OFF."""
    trace.configure(enabled=False, sample_ratio=1.0, ring=128)
    trace.clear()
    yield
    trace.configure(enabled=False, sample_ratio=1.0, ring=128)
    trace.clear()


class TestSpanModel:
    def test_disarmed_is_noop(self):
        s = trace.root_span("anything")
        assert s is trace._NOOP
        assert trace.span("child") is trace._NOOP
        assert trace.inject() is None
        assert trace.linked("eval", "x") is None
        trace.add_event("ignored")  # must not raise
        assert trace.traces() == []

    def test_nesting_and_parent_ids(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.test", method="t") as root:
            with trace.span("fsm.test") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
        [summary] = trace.traces()
        assert summary["Complete"]
        full = trace.get_trace(summary["TraceID"])
        names = {s["Name"] for s in full["Spans"]}
        assert names == {"rpc.test", "fsm.test"}

    def test_durations_are_monotonic_ms(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.sleep"):
            time.sleep(0.02)
        full = trace.get_trace(trace.traces()[0]["TraceID"])
        [span] = full["Spans"]
        assert span["DurationMs"] >= 15.0
        assert abs(span["Start"] - time.time()) < 5.0  # wall anchor

    def test_carrier_roundtrip_resume(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.origin") as root:
            carrier = trace.inject()
        assert carrier["TraceID"] == root.trace_id
        assert carrier["SpanID"] == root.span_id
        # Another "process"/thread resumes from the carrier alone.
        with trace.resume(carrier, "worker.remote") as remote:
            assert remote.trace_id == root.trace_id
            assert remote.parent_id == root.span_id

    def test_resume_prefers_ambient_context(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.a") as a:
            with trace.resume({"TraceID": "f" * 32, "SpanID": "b" * 16},
                              "nested") as nested:
                assert nested.trace_id == a.trace_id

    def test_links_connect_async_hops(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.enqueue"):
            trace.link("eval", "ev-123")
        carrier = trace.linked("eval", "ev-123")
        assert carrier is not None
        with trace.resume(carrier, "worker.dequeue"):
            pass
        full = trace.get_trace(carrier["TraceID"])
        assert {s["Name"] for s in full["Spans"]} == {"rpc.enqueue",
                                                      "worker.dequeue"}

    def test_record_span_synthesizes_queue_wait(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.q"):
            carrier = trace.inject()
        start = time.monotonic()
        time.sleep(0.01)
        trace.record_span(carrier, "broker.wait", start, eval="e")
        full = trace.get_trace(carrier["TraceID"])
        wait = next(s for s in full["Spans"] if s["Name"] == "broker.wait")
        assert wait["DurationMs"] >= 5.0

    def test_ring_is_bounded_at_configured_size(self):
        trace.configure(enabled=True, ring=4)
        for i in range(20):
            with trace.root_span("rpc.n", i=i):
                pass
        assert len(trace.traces()) <= 4
        trace.configure(ring=64)
        for i in range(80):
            with trace.root_span("rpc.n", i=i):
                pass
        assert len(trace.traces()) <= 64

    def test_attach_without_spans_creates_no_trace(self):
        """A carrier-bearing frame whose handler never opens a span (raft
        replication on followers) must not pollute the ring with empty
        traces — the local trace is created lazily at first span."""
        trace.configure(enabled=True)
        carrier = {"TraceID": "a" * 32, "SpanID": "b" * 16,
                   "Sampled": True}
        with trace.attach(carrier):
            assert trace.inject() == carrier  # context still propagates
        assert trace.traces() == []
        assert trace.get_trace("a" * 32) is None
        # ...but a handler that DOES span joins the remote trace.
        with trace.attach(carrier):
            with trace.span("rpc.Handled") as s:
                assert s.trace_id == "a" * 32
        [summary] = trace.traces()
        assert summary["TraceID"] == "a" * 32

    def test_head_sampling_zero_drops_clean_traces(self):
        trace.configure(enabled=True, sample_ratio=0.0)
        with trace.root_span("rpc.clean"):
            pass
        assert trace.traces() == []

    def test_error_tail_rule_retains_unsampled_trace(self):
        trace.configure(enabled=True, sample_ratio=0.0)
        with trace.root_span("rpc.faulty"):
            trace.add_event("failpoint", site="x", mode="error")
        [summary] = trace.traces()
        assert summary["Error"]

    def test_failpoint_trigger_lands_on_active_span(self):
        from nomad_tpu.resilience import failpoints

        trace.configure(enabled=True)
        failpoints.arm("trace.test.site", "delay", delay=0.0, count=1)
        try:
            with trace.root_span("rpc.fp"):
                failpoints.fire("trace.test.site")
        finally:
            failpoints.disarm("trace.test.site")
        full = trace.get_trace(trace.traces()[0]["TraceID"])
        [span] = full["Spans"]
        events = {e["Name"]: e["Attrs"] for e in span["Events"]}
        assert events["failpoint"]["site"] == "trace.test.site"

    def test_retry_attempts_land_on_active_span(self):
        from nomad_tpu.resilience.retry import Backoff, RetryPolicy

        trace.configure(enabled=True)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("boom")
            return "ok"

        policy = RetryPolicy(max_attempts=5,
                             backoff=Backoff(base=0.001, cap=0.002))
        with trace.root_span("rpc.retry"):
            assert policy.call(flaky) == "ok"
        full = trace.get_trace(trace.traces()[0]["TraceID"])
        [span] = full["Spans"]
        retries = [e for e in span["Events"] if e["Name"] == "retry"]
        assert len(retries) == 2
        assert retries[0]["Attrs"]["error"] == "ConnectionError"

    def test_metrics_bridge_records_nomad_trace_samples(self):
        from nomad_tpu import telemetry

        telemetry.configure(collection_interval=3600.0)
        trace.configure(enabled=True)
        with trace.root_span("rpc.bridged"):
            pass
        snap = telemetry.snapshot()
        assert any(s["Name"] == "nomad.trace.rpc.bridged"
                   for s in snap["Samples"])

    def test_chrome_export_is_valid_trace_event_json(self):
        trace.configure(enabled=True)
        with trace.root_span("rpc.export"):
            with trace.span("fsm.export"):
                trace.add_event("failpoint", site="s", mode="drop")
        out = trace.export_chrome()
        json.loads(json.dumps(out))  # JSON-serializable end to end
        events = out["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        assert len(complete) == 2
        for e in complete:
            assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert any(e["ph"] == "i" for e in events)  # the failpoint instant
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in events)

    def test_wire_envelope_carries_and_attaches(self):
        """The msgpack envelope leg: a request built inside a trace carries
        the carrier; the server dispatcher attach()es it so handler spans
        join the caller's trace (rpc/wire.py + rpc/server.py)."""
        from nomad_tpu.rpc.wire import MessageCodec

        trace.configure(enabled=True)
        with trace.root_span("rpc.client_side") as origin:
            frame = MessageCodec.request(1, "Status.Ping", {},
                                         trace=trace.inject())
        assert frame["Trace"]["TraceID"] == origin.trace_id
        # Simulated remote process: attach + a handler span.
        with trace.attach(frame["Trace"]):
            with trace.span("rpc.Status.Ping") as handler:
                assert handler.trace_id == origin.trace_id
                assert handler.parent_id == origin.span_id
        assert MessageCodec.request(2, "m", {}).get("Trace") is None


SLEEPER_JOB = '''
job "tracejob" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sh" args = ["-c", "sleep 2"] }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}
'''


class TestEndToEndTrace:
    """The acceptance drive: one register -> running mock task, one
    connected trace spanning both sides of the control plane."""

    @pytest.fixture()
    def dev_agent(self, tmp_path):
        config = AgentConfig.dev()
        config.http_port = 0
        config.data_dir = str(tmp_path / "agent")
        agent = Agent(config)
        agent.start()
        api = APIClient(address=f"http://127.0.0.1:{agent.http.port}")
        yield agent, api
        agent.shutdown()

    def test_register_to_running_task_is_one_trace(self, dev_agent):
        agent, api = dev_agent
        # Runtime toggle through the debug endpoint (the same surface the
        # `trace` CLI drives).
        status = api.agent.configure_trace(enabled=True, sample_ratio=1.0)
        assert status["Enabled"] is True

        job = parse_job(SLEEPER_JOB)
        job.init_fields()
        eval_id, _ = api.jobs.register(job)
        assert eval_id
        assert wait_for(lambda: api.evaluations.info(eval_id)[0]["Status"]
                        == "complete", timeout=40)
        assert wait_for(
            lambda: (allocs := api.jobs.allocations("tracejob")[0])
            and allocs[0]["ClientStatus"] in ("running", "complete"),
            timeout=40, msg="alloc never started")

        def register_trace():
            listing = api.agent.traces()
            for t in listing.get("Traces", ()):
                if t["Root"] != "rpc.Job.Register":
                    continue
                full = api.agent.trace(t["TraceID"])["Trace"]
                names = {s["Name"] for s in full["Spans"]}
                if "client.task_start" in names:
                    return full
            return None

        assert wait_for(lambda: register_trace() is not None, timeout=30,
                        msg="client-side spans never joined the trace")
        full = register_trace()
        spans = full["Spans"]
        # One trace id across every span.
        assert {s["TraceID"] for s in spans} == {full["TraceID"]}
        assert len(spans) >= 6
        names = {s["Name"] for s in spans}
        # Server side: broker, worker stage, plan apply, fsm.
        assert "broker.wait" in names
        assert names & {"worker.window", "worker.process_eval",
                        "worker.invoke_scheduler"}
        assert "plan.apply" in names
        assert any(n.startswith("fsm.") for n in names)
        # Client side: alloc pickup + task launch.
        assert "client.alloc_run" in names
        assert "client.task_start" in names

        # Chrome trace-event export: valid JSON with complete events.
        chrome = api.agent.trace(full["TraceID"], chrome=True)
        events = chrome["traceEvents"]
        assert events and all("ph" in e and "ts" in e and "pid" in e
                              for e in events)
        assert any(e["ph"] == "X" and e["name"] == "client.task_start"
                   for e in events)
        json.dumps(chrome)

        # Unknown ids 404 on both the full and chrome paths.
        from nomad_tpu.api import APIError

        with pytest.raises(APIError) as exc:
            api.agent.trace("f" * 32)
        assert exc.value.code == 404
        with pytest.raises(APIError) as exc:
            api.agent.trace("f" * 32, chrome=True)
        assert exc.value.code == 404

        # Disable + clear puts the agent back in the disarmed state.
        api.agent.configure_trace(enabled=False)
        api.agent.clear_traces()
        assert api.agent.traces()["Traces"] == []
        from nomad_tpu.telemetry import trace as trace_mod

        assert trace_mod.span("x") is trace_mod._NOOP
