"""Shared test helpers (one wait_for instead of a copy per file)."""

from __future__ import annotations

import time
from typing import Callable, Optional


def wait_for(cond: Callable[[], object], timeout: float = 40.0,
             interval: float = 0.05, msg: Optional[str] = None) -> bool:
    """Poll `cond` until truthy. Returns True on success; on timeout,
    fails the test when `msg` is given, else returns False (callers
    assert). Generous default: full-suite runs share a loaded machine."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    if msg is not None:
        import pytest

        pytest.fail(f"timeout waiting for {msg}")
    return False
