"""Feasibility checker parity grid (reference: scheduler/feasible_test.go
— the operand/target/driver case grids). The iterator-chain tests
(Static/Random iterators, FeasibilityWrapper) have tensor analogues in
test_tensor_and_kernels.py; this file ports the semantic grids that must
match the reference bit for bit: constraint operands (including the Go
int-to-string version fallback), lexical ordering, version constraints,
regexp, target resolution, the driver checker's boolean parsing, and the
combined constraint checker."""

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import Constraint
from nomad_tpu.tensor.constraints import (
    check_constraint,
    node_has_drivers,
    node_meets_constraints,
    resolve_target,
)


class TestCheckConstraint:
    """(reference: TestCheckConstraint)"""

    CASES = [
        ("=", "foo", "foo", True),
        ("is", "foo", "foo", True),
        ("==", "foo", "foo", True),
        ("!=", "foo", "foo", False),
        ("!=", "foo", "bar", True),
        ("not", "foo", "bar", True),
        ("version", "1.2.3", "~> 1.0", True),
        ("regexp", "foobarbaz", r"[\w]+", True),
        ("<", "foo", "bar", False),
    ]

    @pytest.mark.parametrize("op,l,r,want", CASES,
                             ids=[f"{c[0]}-{c[1]}-{c[2]}" for c in CASES])
    def test_operand(self, op, l, r, want):
        assert check_constraint(op, l, r) is want


class TestCheckLexicalOrder:
    """(reference: TestCheckLexicalOrder)"""

    CASES = [
        ("<", "bar", "foo", True),
        ("<=", "foo", "foo", True),
        (">", "bar", "foo", False),
        (">=", "bar", "bar", True),
        (">", 1, "foo", False),  # non-string: never feasible
    ]

    @pytest.mark.parametrize("op,l,r,want", CASES)
    def test_lexical(self, op, l, r, want):
        assert check_constraint(op, l, r) is want


class TestCheckVersionConstraint:
    """(reference: TestCheckVersionConstraint)"""

    CASES = [
        ("1.2.3", "~> 1.0", True),
        ("1.2.3", ">= 1.0, < 1.4", True),
        ("2.0.1", "~> 1.0", False),
        ("1.4", ">= 1.0, < 1.4", False),
        (1, "~> 1.0", True),  # Go's int fallback: 1 -> "1" -> 1.0.0
    ]

    @pytest.mark.parametrize("l,r,want", CASES)
    def test_version(self, l, r, want):
        assert check_constraint("version", l, r) is want


class TestCheckRegexpConstraint:
    """(reference: TestCheckRegexpConstraint — search semantics, anchors
    honored, non-strings and bad patterns infeasible)"""

    CASES = [
        ("foobar", "bar", True),
        ("foobar", "^foo", True),
        ("foobar", "^bar", False),
        ("zipzap", "foo", False),
        (1, "foo", False),
        ("foobar", "(unclosed", False),  # malformed pattern: infeasible
    ]

    @pytest.mark.parametrize("l,r,want", CASES)
    def test_regexp(self, l, r, want):
        assert check_constraint("regexp", l, r) is want


class TestResolveConstraintTarget:
    """(reference: TestResolveConstraintTarget)"""

    def test_targets(self):
        node = mock.node()
        cases = [
            ("${node.unique.id}", node.ID, True),
            ("${node.datacenter}", node.Datacenter, True),
            ("${node.unique.name}", node.Name, True),
            ("${node.class}", node.NodeClass, True),
            ("${node.foo}", None, False),
            ("${attr.kernel.name}", node.Attributes["kernel.name"], True),
            ("${attr.rand}", None, False),
            ("${meta.pci-dss}", node.Meta["pci-dss"], True),
            ("${meta.rand}", None, False),
        ]
        for target, want_val, want_ok in cases:
            val, ok = resolve_target(target, node)
            assert ok is want_ok, target
            if ok:
                assert val == want_val, target


class TestDriverChecker:
    """(reference: TestDriverChecker — the driver attribute must parse as
    a TRUE boolean; '0' and 'False' both fail)"""

    def test_boolean_parsing(self):
        drivers = ["exec", "foo"]
        # Go strconv.ParseBool semantics: the reference accepts every
        # Go boolean literal, not just "1"/"true".
        cases = [("1", True), ("0", False), ("true", True),
                 ("False", False), ("T", True), ("t", True),
                 ("TRUE", True), ("f", False), ("yes", False)]
        for raw, want in cases:
            node = mock.node()
            node.Attributes["driver.foo"] = raw
            assert node_has_drivers(node, drivers) is want, raw
        # Missing driver attribute entirely: infeasible.
        node = mock.node()
        node.Attributes.pop("driver.foo", None)
        assert not node_has_drivers(node, drivers)


class TestConstraintChecker:
    """(reference: TestConstraintChecker — all constraints must hold;
    any unresolvable target or failed operand rejects the node)"""

    def test_combined(self):
        nodes = [mock.node() for _ in range(4)]
        nodes[0].Attributes["kernel.name"] = "freebsd"
        nodes[1].Datacenter = "dc2"
        nodes[2].NodeClass = "large"
        constraints = [
            Constraint(Operand="=", LTarget="${node.datacenter}",
                       RTarget="dc1"),
            Constraint(Operand="is", LTarget="${attr.kernel.name}",
                       RTarget="linux"),
            Constraint(Operand="is", LTarget="${node.class}",
                       RTarget="large"),
        ]
        results = [node_meets_constraints(n, constraints) for n in nodes]
        # node 3 has default class "" != large -> also infeasible.
        assert results == [False, False, True, False]
