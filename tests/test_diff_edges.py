"""Diff-engine edge matrix (reference: nomad/structs/diff_test.go's wider
case grid — group rename, periodic/update-strategy/log/artifact/restart
changes, None<->object transitions, service add/remove)."""

from nomad_tpu import mock
from nomad_tpu.structs import (
    PeriodicConfig,
    RestartPolicy,
    Service,
    ServiceCheck,
    TaskArtifact,
    UpdateStrategy,
)
from nomad_tpu.structs.diff import (
    DiffTypeAdded,
    DiffTypeDeleted,
    DiffTypeEdited,
    DiffTypeNone,
    job_diff,
)
from nomad_tpu.structs.structs import SECOND, LogConfig


def _obj(diff, name):
    return next((o for o in diff.Objects if o.Name == name), None)


def _tg(jd, name):
    return next((g for g in jd.TaskGroups if g.Name == name), None)


def _task(gd, name):
    return next((t for t in gd.Tasks if t.Name == name), None)


class TestGroupMatrix:
    def test_group_rename_is_delete_plus_add(self):
        old = mock.job()
        new = old.copy()
        new.TaskGroups[0].Name = "renamed"
        jd = job_diff(old, new)
        assert jd.Type == DiffTypeEdited
        assert _tg(jd, old.TaskGroups[0].Name).Type == DiffTypeDeleted
        assert _tg(jd, "renamed").Type == DiffTypeAdded

    def test_restart_policy_change(self):
        old = mock.job()
        old.TaskGroups[0].RestartPolicy = RestartPolicy(
            Attempts=2, Interval=60 * SECOND, Delay=5 * SECOND, Mode="fail")
        new = old.copy()
        new.TaskGroups[0].RestartPolicy.Attempts = 9
        jd = job_diff(old, new)
        gd = _tg(jd, old.TaskGroups[0].Name)
        rp = _obj(gd, "RestartPolicy")
        assert rp is not None and rp.Type == DiffTypeEdited
        field = next(f for f in rp.Fields if f.Name == "Attempts")
        assert field.Old == "2" and field.New == "9"


class TestJobLevelMatrix:
    def test_periodic_added(self):
        old = mock.job()
        new = old.copy()
        new.Periodic = PeriodicConfig(Enabled=True, Spec="*/15 * * * *",
                                      SpecType="cron")
        jd = job_diff(old, new)
        per = _obj(jd, "Periodic")
        assert per is not None and per.Type == DiffTypeAdded

    def test_update_strategy_edited(self):
        old = mock.job()
        old.Update = UpdateStrategy(Stagger=10 * SECOND, MaxParallel=1)
        new = old.copy()
        new.Update.MaxParallel = 4
        jd = job_diff(old, new)
        upd = _obj(jd, "Update")
        assert upd is not None and upd.Type == DiffTypeEdited

    def test_update_strategy_removed(self):
        old = mock.job()
        old.Update = UpdateStrategy(Stagger=10 * SECOND, MaxParallel=1)
        new = old.copy()
        new.Update = None
        jd = job_diff(old, new)
        upd = _obj(jd, "Update")
        assert upd is not None and upd.Type == DiffTypeDeleted


class TestTaskMatrix:
    def _task_diff(self, mutate):
        old = mock.job()
        new = old.copy()
        mutate(new.TaskGroups[0].Tasks[0])
        jd = job_diff(old, new)
        gd = _tg(jd, old.TaskGroups[0].Name)
        return _task(gd, old.TaskGroups[0].Tasks[0].Name)

    def test_log_config_edited(self):
        def mutate(task):
            task.LogConfig = LogConfig(MaxFiles=3, MaxFileSizeMB=5)
        td = self._task_diff(mutate)
        lc = _obj(td, "LogConfig")
        assert lc is not None and lc.Type in (DiffTypeEdited, DiffTypeAdded)

    def test_artifact_added(self):
        def mutate(task):
            task.Artifacts.append(TaskArtifact(
                GetterSource="http://example.com/x.tgz"))
        td = self._task_diff(mutate)
        art = _obj(td, "Artifact")
        assert art is not None and art.Type == DiffTypeAdded

    def test_service_added_and_removed(self):
        old = mock.job()
        old.TaskGroups[0].Tasks[0].Services = [Service(
            Name="old-svc", PortLabel="main")]
        new = old.copy()
        new.TaskGroups[0].Tasks[0].Services = [Service(
            Name="new-svc", PortLabel="main")]
        jd = job_diff(old, new)
        gd = _tg(jd, old.TaskGroups[0].Name)
        td = _task(gd, old.TaskGroups[0].Tasks[0].Name)
        names = {(o.Name, o.Type) for o in td.Objects}
        assert ("Service", DiffTypeAdded) in names
        assert ("Service", DiffTypeDeleted) in names

    def test_check_interval_edit_nested(self):
        old = mock.job()
        old.TaskGroups[0].Tasks[0].Services = [Service(
            Name="svc", PortLabel="main",
            Checks=[ServiceCheck(Name="c", Type="tcp",
                                 Interval=10 * SECOND,
                                 Timeout=2 * SECOND)])]
        new = old.copy()
        new.TaskGroups[0].Tasks[0].Services[0].Checks[0].Interval = \
            30 * SECOND
        jd = job_diff(old, new)
        gd = _tg(jd, old.TaskGroups[0].Name)
        td = _task(gd, old.TaskGroups[0].Tasks[0].Name)
        svc = _obj(td, "Service")
        assert svc is not None and svc.Type == DiffTypeEdited
        chk = _obj(svc, "Check")
        assert chk is not None and chk.Type == DiffTypeEdited
