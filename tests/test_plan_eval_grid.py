"""Plan-evaluation parity grid (reference: nomad/plan_apply_test.go —
the EvalPlan partial/AllAtOnce commits and the EvalNodePlan per-node
fit matrix). The concurrency design (optimistic overlay, verify/apply
overlap, grouped commits) is covered by test_plan_overlap.py; this file
pins the admission SEMANTICS the applier must share with the
reference."""

from nomad_tpu import mock
from nomad_tpu.server.plan_apply import _evaluate_node_plan, evaluate_plan
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Plan
from nomad_tpu.structs.structs import (
    AllocDesiredStatusEvict,
    NodeStatusDown,
)


def _store():
    return StateStore()


def _fitting_alloc(node=None):
    alloc = mock.alloc()
    alloc.Job = None
    if node is not None:
        alloc.NodeID = node.ID
    return alloc


def _consume_all(node, alloc):
    """Make `alloc` consume the node entirely (the reference's
    node.Resources = alloc.Resources; node.Reserved = nil)."""
    alloc.NodeID = node.ID
    node.Resources = alloc.Resources.copy()
    node.Reserved = None


class TestEvalPlan:
    def test_simple_full_commit(self):
        """(reference: TestPlanApply_EvalPlan_Simple)"""
        state = _store()
        node = mock.node()
        state.upsert_node(1000, node)
        snap = state.snapshot()
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        result = evaluate_plan(snap, plan)
        assert result.NodeAllocation == plan.NodeAllocation
        assert result.RefreshIndex == 0

    def test_partial_commit_sets_refresh(self):
        """(reference: TestPlanApply_EvalPlan_Partial): the fitting node
        commits, the overfull one is dropped, and RefreshIndex tells the
        worker to resync."""
        state = _store()
        node, node2 = mock.node(), mock.node()
        state.upsert_node(1000, node)
        state.upsert_node(1001, node2)
        snap = state.snapshot()
        big = _fitting_alloc(node2)
        big.Resources = node2.Resources.copy()
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)],
                                    node2.ID: [big]})
        result = evaluate_plan(snap, plan)
        assert node.ID in result.NodeAllocation
        assert node2.ID not in result.NodeAllocation
        assert result.RefreshIndex == 1001

    def test_all_at_once_partial_commits_nothing(self):
        """(reference: TestPlanApply_EvalPlan_Partial_AllAtOnce)"""
        state = _store()
        node, node2 = mock.node(), mock.node()
        state.upsert_node(1000, node)
        state.upsert_node(1001, node2)
        snap = state.snapshot()
        big = _fitting_alloc(node2)
        big.Resources = node2.Resources.copy()
        plan = Plan(AllAtOnce=True,
                    NodeAllocation={node.ID: [_fitting_alloc(node)],
                                    node2.ID: [big]})
        result = evaluate_plan(snap, plan)
        assert result.NodeAllocation == {}
        assert result.NodeUpdate == {}
        assert result.RefreshIndex == 1001


class TestEvalNodePlan:
    def _ready_node_with_full_alloc(self, evict_existing=False):
        state = _store()
        node = mock.node()
        alloc = mock.alloc()
        alloc.Job = None
        _consume_all(node, alloc)
        if evict_existing:
            alloc.DesiredStatus = AllocDesiredStatusEvict
        state.upsert_node(1000, node)
        state.upsert_allocs(1001, [alloc])
        return state, node, alloc

    def test_simple_fits(self):
        """(reference: TestPlanApply_EvalNodePlan_Simple)"""
        state = _store()
        node = mock.node()
        state.upsert_node(1000, node)
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_not_ready_rejects(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeNotReady)"""
        state = _store()
        node = mock.node()
        node.Status = "initializing"
        state.upsert_node(1000, node)
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert not _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_drain_rejects(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeDrain)"""
        state = _store()
        node = mock.node()
        node.Drain = True
        state.upsert_node(1000, node)
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert not _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_not_exist_rejects(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeNotExist)"""
        state = _store()
        ghost = "12345678-abcd-efab-cdef-123456789abc"
        plan = Plan(NodeAllocation={ghost: [_fitting_alloc()]})
        assert not _evaluate_node_plan(state.snapshot(), plan, ghost)

    def test_node_full_rejects(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeFull)"""
        state, node, _ = self._ready_node_with_full_alloc()
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert not _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_update_existing_fits(self):
        """(reference: TestPlanApply_EvalNodePlan_UpdateExisting): a plan
        re-placing the SAME alloc (in-place update) discounts the live
        copy and fits on a full node."""
        state, node, alloc = self._ready_node_with_full_alloc()
        plan = Plan(NodeAllocation={node.ID: [alloc]})
        assert _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_full_with_planned_evict_fits(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeFull_Evict)"""
        state, node, alloc = self._ready_node_with_full_alloc()
        evict = alloc.copy()
        evict.DesiredStatus = AllocDesiredStatusEvict
        plan = Plan(NodeUpdate={node.ID: [evict]},
                    NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_full_with_terminal_existing_fits(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeFull_AllocEvict):
        an existing alloc already marked evict doesn't count against
        capacity."""
        state, node, _ = self._ready_node_with_full_alloc(
            evict_existing=True)
        plan = Plan(NodeAllocation={node.ID: [_fitting_alloc(node)]})
        assert _evaluate_node_plan(state.snapshot(), plan, node.ID)

    def test_node_down_evict_only_fits(self):
        """(reference: TestPlanApply_EvalNodePlan_NodeDown_EvictOnly):
        a DOWN node accepts pure evictions (no placements)."""
        state = _store()
        node = mock.node()
        alloc = mock.alloc()
        alloc.Job = None
        _consume_all(node, alloc)
        node.Status = NodeStatusDown
        state.upsert_node(1000, node)
        state.upsert_allocs(1001, [alloc])
        evict = alloc.copy()
        evict.DesiredStatus = AllocDesiredStatusEvict
        plan = Plan(NodeUpdate={node.ID: [evict]})
        assert _evaluate_node_plan(state.snapshot(), plan, node.ID)
