"""Cluster restart recovery: a server must come back from its durable
raft state (CRC-framed log + stable store) as a member of the cluster it
belonged to, not as a dormant virgin (reference: hashicorp/raft's
peers.json + nomad/server.go setupRaft restore path).

Round-4 regression class: the peer set lived only in memory, so EVERY
restarted cluster was dead — each server's bootstrap-expect probe saw an
existing cluster (log > 0) and deferred forever while nobody was
electable."""

import socket
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.structs.structs import EvalStatusComplete


from helpers import wait_for  # noqa: E402

# Cluster boots + elections under a loaded box: a direct apply can race
# a leadership flap right after wait_leader's sample (NotLeaderError) —
# the same churn class the TLS cluster test retries through. One retry
# absorbs it; a real recovery bug fails both attempts.
pytestmark = pytest.mark.timing_retry


def free_ports(n):
    """n distinct ports BELOW the ephemeral range: the agents' own
    http_port=0 binds draw from the ephemeral range, so a port probed
    there can be stolen between reservation and use."""
    import random

    ports = []
    rng = random.Random()
    while len(ports) < n:
        cand = rng.randrange(20000, 28000)
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", cand))
        except OSError:
            continue
        finally:
            s.close()
        if cand not in ports:
            ports.append(cand)
    return ports


def free_port():
    return free_ports(1)[0]


def boot(name, data_dir, rpc_port, serf_port=0, expect=1, join=None,
         schedulers=1):
    last = None
    for _ in range(10):  # ride out TIME_WAIT on quick restarts
        try:
            a = Agent(AgentConfig(server_enabled=True, client_enabled=False,
                                  http_port=0, rpc_port=rpc_port,
                                  serf_port=serf_port,
                                  bootstrap_expect=expect,
                                  node_name=name, num_schedulers=schedulers,
                                  data_dir=str(data_dir),
                                  start_join=list(join or [])))
            a.start()
            return a
        except OSError as e:
            last = e
            time.sleep(0.5)
    raise last


def wait_leader(agents, timeout=30):
    assert wait_for(lambda: sum(
        1 for a in agents if a.server.is_leader() and a.server._leader) == 1,
        timeout=timeout)
    return next(a for a in agents if a.server.is_leader() and a.server._leader)


def wait_eval(srv, eval_id, timeout=30):
    assert wait_for(lambda: (
        (e := srv.state.eval_by_id(eval_id)) is not None
        and e.Status == EvalStatusComplete), timeout=timeout)


class TestSingleServerRestart:
    def test_restart_recovers_state_and_reelects(self, tmp_path):
        port = free_port()
        a = boot("s1", tmp_path, port)
        try:
            wait_leader([a])
            a.server.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = a.server.job_register(job)
            wait_eval(a.server, eval_id)
            n1 = len(a.server.state.allocs_by_job(job.ID))
            assert n1 > 0
        finally:
            a.shutdown()

        a2 = boot("s1", tmp_path, port)
        try:
            wait_leader([a2])
            # Durable log replayed: jobs, allocs, and nodes all back.
            assert len(a2.server.state.allocs_by_job(job.ID)) == n1
            assert len(a2.server.state.nodes()) == 1
            # And the recovered server still schedules.
            job2 = mock.job()
            eval2, _, _ = a2.server.job_register(job2)
            wait_eval(a2.server, eval2)
        finally:
            a2.shutdown()


class TestClusterColdRestart:
    @pytest.mark.timing_retry
    def test_full_cluster_cold_restart_reelects_and_serves(self, tmp_path):
        """All three servers stop, then all three come back with their
        data dirs: the stored peer sets make them electable again, one
        leader emerges, and the replicated state is intact everywhere."""
        rpc = [free_port() for _ in range(3)]
        serf = [free_port() for _ in range(3)]
        dirs = [tmp_path / f"s{i}" for i in range(3)]
        join = [f"127.0.0.1:{serf[0]}"]

        agents = [boot("s0", dirs[0], rpc[0], serf[0], expect=3)]
        agents += [boot(f"s{i}", dirs[i], rpc[i], serf[i], expect=3,
                        join=join) for i in (1, 2)]
        job = mock.job()
        try:
            leader = wait_leader(agents)
            leader.server.node_register(mock.node())
            eval_id, _, _ = leader.server.job_register(job)
            wait_eval(leader.server, eval_id)
            # Replicated everywhere before the outage.
            for a in agents:
                assert wait_for(lambda a=a: len(
                    a.server.state.allocs_by_job(job.ID)) > 0)
            n_allocs = len(leader.server.state.allocs_by_job(job.ID))
        finally:
            for a in agents:
                a.shutdown()

        # Restart with FRESH serf ports: gossip identity is rediscovered
        # via join (the reference tolerates serf address changes the same
        # way); the raft identity that must survive is the fixed RPC
        # address, restored from the stable store's peer set.
        a0 = boot("s0", dirs[0], rpc[0], 0, expect=3)
        ml = a0.cluster.membership.memberlist
        join2 = [f"{ml.addr}:{ml.port}"]
        agents = [a0] + [boot(f"s{i}", dirs[i], rpc[i], 0, expect=3,
                              join=join2) for i in (1, 2)]
        try:
            # 90s: a loaded 1-core CI box has double-failed the 45s
            # margin even through the timing retry.
            leader = wait_leader(agents, timeout=90)
            for a in agents:
                assert wait_for(lambda a=a: len(
                    a.server.state.allocs_by_job(job.ID)) == n_allocs,
                    timeout=30)
            # The recovered cluster serves: a fresh job schedules.
            job2 = mock.job()
            eval2, _, _ = leader.server.job_register(job2)
            wait_eval(leader.server, eval2, timeout=90)
        finally:
            for a in agents:
                a.shutdown()


class TestClientRestart:
    def test_client_restart_keeps_node_identity_and_alloc(self, tmp_path):
        """A restarted client agent must come back as the SAME node (the
        persisted client-id) and re-adopt its allocation instead of the
        server rescheduling it onto a 'new' node."""
        port = free_port()

        def boot_both():
            a = Agent(AgentConfig(server_enabled=True, client_enabled=True,
                                  http_port=0, rpc_port=port, serf_port=0,
                                  bootstrap_expect=1, node_name="s1",
                                  num_schedulers=1,
                                  data_dir=str(tmp_path)))
            a.start()
            return a

        a = boot_both()
        try:
            wait_leader([a])
            assert wait_for(lambda: any(
                n.Status == "ready" for n in a.server.state.nodes()),
                timeout=30)
            node_id = a.server.state.nodes()[0].ID
            job = mock.job()
            tg = job.TaskGroups[0]
            tg.Count = 1
            task = tg.Tasks[0]
            task.Driver = "mock_driver"
            task.Config = {"run_for": 300}
            task.Resources.Networks = []
            task.Services = []
            eval_id, _, _ = a.server.job_register(job)
            wait_eval(a.server, eval_id)
            assert wait_for(lambda: [
                al for al in a.server.state.allocs_by_job(job.ID)
                if al.ClientStatus == "running"], timeout=30)
            alloc_id = a.server.state.allocs_by_job(job.ID)[0].ID
        finally:
            a.shutdown()

        a2 = boot_both()
        try:
            wait_leader([a2])
            # Same node identity: exactly one node, same ID, ready again.
            assert wait_for(lambda: any(
                n.ID == node_id and n.Status == "ready"
                for n in a2.server.state.nodes()), timeout=30)
            assert len(a2.server.state.nodes()) == 1
            # Same allocation, re-adopted (running), no reschedule.
            assert wait_for(lambda: any(
                al.ID == alloc_id and al.ClientStatus == "running"
                for al in a2.server.state.allocs_by_job(job.ID)),
                timeout=30)
            live = [al for al in a2.server.state.allocs_by_job(job.ID)
                    if not al.terminal_status()]
            assert [al.ID for al in live] == [alloc_id]
        finally:
            a2.shutdown()


class TestTLSRestart:
    def test_tls_server_restart_recovers(self, tmp_path):
        """Restart with mutual TLS on: certificates reload, the stored
        peer set makes the server electable, and the TLS-muxed raft/RPC
        planes come back — the full operator restart path with
        verify_incoming enabled."""
        from test_tls import issue_cert, make_ca

        ca_key, ca_crt = make_ca(str(tmp_path))
        key, crt = issue_cert(str(tmp_path), ca_key, ca_crt, "server")
        port = free_port()

        def boot_tls():
            a = Agent(AgentConfig(server_enabled=True, client_enabled=False,
                                  http_port=0, rpc_port=port, serf_port=0,
                                  bootstrap_expect=1, node_name="tls1",
                                  num_schedulers=1,
                                  data_dir=str(tmp_path / "data"),
                                  tls_enable_rpc=True,
                                  tls_ca_file=str(ca_crt),
                                  tls_cert_file=str(crt),
                                  tls_key_file=str(key),
                                  tls_verify_incoming=True))
            a.start()
            return a

        a = boot_tls()
        try:
            wait_leader([a])
            a.server.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = a.server.job_register(job)
            wait_eval(a.server, eval_id)
            n1 = len(a.server.state.allocs_by_job(job.ID))
            assert n1 > 0
        finally:
            a.shutdown()

        a2 = boot_tls()
        try:
            wait_leader([a2])
            assert len(a2.server.state.allocs_by_job(job.ID)) == n1
            job2 = mock.job()
            eval2, _, _ = a2.server.job_register(job2)
            wait_eval(a2.server, eval2)
        finally:
            a2.shutdown()
