"""Tensor layer, kernel, pipelined placer, and multi-chip sharding tests."""

import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.structs import Constraint, compute_node_class
from nomad_tpu.tensor import ClassEligibility, NodeTensor, TensorIndex
from nomad_tpu.tensor.node_table import RES_DIMS, resources_vec


class TestNodeTensor:
    def test_upsert_and_usage(self):
        nt = NodeTensor()
        n = mock.node()
        nt.upsert_node(n)
        row = nt.row_of[n.ID]
        assert nt.capacity[row][0] == 4000
        assert nt.usage[row][0] == 100  # reserved CPU counts as usage
        assert nt.score_cap[row][0] == 3900
        a = mock.alloc()
        a.NodeID = n.ID
        nt.add_alloc_usage(a)
        assert nt.usage[row][0] == 600
        nt.remove_alloc_usage(a)
        assert nt.usage[row][0] == 100

    def test_row_reuse_and_growth(self):
        nt = NodeTensor(capacity_hint=2)
        nodes = [mock.node() for _ in range(100)]
        for n in nodes:
            nt.upsert_node(n)
        assert nt.n_rows >= 100
        rows = {nt.row_of[n.ID] for n in nodes}
        assert len(rows) == 100
        nt.remove_node(nodes[0].ID)
        n_new = mock.node()
        nt.upsert_node(n_new)
        assert nt.row_of[n_new.ID] in range(nt.n_rows)

    def test_device_sync_dirty_rows(self):
        nt = NodeTensor()
        n = mock.node()
        nt.upsert_node(n)
        d1 = nt.device_arrays()
        row = nt.row_of[n.ID]
        a = mock.alloc()
        a.NodeID = n.ID
        nt.add_alloc_usage(a)
        d2 = nt.device_arrays()
        assert float(d2["usage"][row][0]) == nt.usage[row][0]

    def test_reserved_change_preserves_alloc_usage(self):
        nt = NodeTensor()
        n = mock.node()
        nt.upsert_node(n)
        a = mock.alloc()
        a.NodeID = n.ID
        nt.add_alloc_usage(a)
        row = nt.row_of[n.ID]
        before = nt.usage[row].copy()
        # Re-upsert with doubled reservation.
        n2 = n.copy()
        n2.Reserved.CPU = 200
        nt.upsert_node(n2)
        assert nt.usage[row][0] == before[0] + 100


class TestClassEligibility:
    def test_class_memoization_and_escape(self):
        nt = NodeTensor()
        nodes = [mock.node() for _ in range(4)]
        nodes[2].Attributes["kernel.name"] = "windows"
        nodes[3].Attributes["unique.special"] = "yes"
        for n in nodes:
            compute_node_class(n)
            nt.upsert_node(n)
        elig = ClassEligibility(nt, nodes)
        cons = [Constraint(LTarget="${attr.kernel.name}", RTarget="linux",
                           Operand="=")]
        mask, table, escaped = elig.job_mask("j1", cons)
        assert not escaped
        rows = [nt.row_of[n.ID] for n in nodes]
        assert mask[rows[0]] and mask[rows[1]] and mask[rows[3]]
        assert not mask[rows[2]]

    def test_escaped_constraint_per_node(self):
        nt = NodeTensor()
        n1, n2 = mock.node(), mock.node()
        n1.Attributes["unique.network.ip-address"] = "10.0.0.1"
        n2.Attributes["unique.network.ip-address"] = "10.0.0.2"
        for n in (n1, n2):
            compute_node_class(n)
            nt.upsert_node(n)
        # Same computed class (unique.* excluded) but different unique attrs.
        assert n1.ComputedClass == n2.ComputedClass
        elig = ClassEligibility(nt, [n1, n2])
        cons = [Constraint(LTarget="${attr.unique.network.ip-address}",
                           RTarget="10.0.0.1", Operand="=")]
        mask, _, escaped = elig.job_mask("j1", cons)
        assert escaped
        assert mask[nt.row_of[n1.ID]]
        assert not mask[nt.row_of[n2.ID]]


class TestPlaceBatchKernel:
    def _inputs(self, n=64, p=8):
        import jax.numpy as jnp

        capacity = np.full((n, RES_DIMS), 1000, np.float32)
        score_cap = np.full((n, 2), 1000, np.float32)
        usage = np.zeros((n, RES_DIMS), np.float32)
        masks = np.ones((1, n), bool)
        demands = np.full((p, RES_DIMS), 100, np.float32)
        return dict(
            capacity=jnp.asarray(capacity), score_cap=jnp.asarray(score_cap),
            usage=jnp.asarray(usage), tg_masks=jnp.asarray(masks),
            job_counts=jnp.zeros(n, jnp.int32), demands=jnp.asarray(demands),
            tg_ids=jnp.zeros(p, jnp.int32), valid=jnp.ones(p, bool),
            noise=jnp.zeros(n, jnp.float32), penalty=jnp.float32(10.0),
            distinct_hosts=jnp.asarray(False),
            banned0=jnp.zeros(n, bool))

    def test_spreads_with_anti_affinity(self):
        from nomad_tpu.scheduler import kernels

        kw = self._inputs()
        res = kernels.place_batch(**kw)
        chosen = np.asarray(res.chosen)
        assert (chosen >= 0).all()
        # Penalty 10 dominates bin-pack deltas: placements spread.
        assert len(set(chosen.tolist())) == 8

    def test_packs_without_penalty(self):
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        kw = self._inputs()
        kw["penalty"] = jnp.float32(0.0)
        res = kernels.place_batch(**kw)
        chosen = np.asarray(res.chosen)
        # Bin packing: everything lands on one node until full.
        assert len(set(chosen.tolist())) == 1

    def test_capacity_exhaustion(self):
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        kw = self._inputs(n=2, p=8)
        kw["tg_masks"] = jnp.ones((1, 2), bool)
        kw["job_counts"] = jnp.zeros(2, jnp.int32)
        kw["noise"] = jnp.zeros(2, jnp.float32)
        kw["banned0"] = jnp.zeros(2, bool)
        # 2 nodes x 1000 cap, 8 placements x 300: only 3 fit per node.
        kw["demands"] = jnp.full((8, RES_DIMS), 300, jnp.float32)
        res = kernels.place_batch(**kw)
        chosen = np.asarray(res.chosen)
        assert (chosen >= 0).sum() == 6
        assert (chosen < 0).sum() == 2

    def test_distinct_hosts(self):
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        kw = self._inputs(n=4, p=8)
        kw["tg_masks"] = jnp.ones((1, 4), bool)
        kw["job_counts"] = jnp.zeros(4, jnp.int32)
        kw["noise"] = jnp.zeros(4, jnp.float32)
        kw["banned0"] = jnp.zeros(4, bool)
        kw["demands"] = jnp.full((8, RES_DIMS), 10, jnp.float32)
        kw["distinct_hosts"] = jnp.asarray(True)
        res = kernels.place_batch(**kw)
        chosen = np.asarray(res.chosen)
        placed = chosen[chosen >= 0]
        assert len(placed) == 4  # one per host, rest fail
        assert len(set(placed.tolist())) == 4


class TestPipelinedPlacer:
    def test_chained_contention(self):
        """Evals in one window contend for capacity device-side."""
        from nomad_tpu.scheduler.pipeline import EvalRequest, PipelinedPlacer

        node = mock.node()  # 3900 usable CPU
        tindex = TensorIndex()
        tindex.nt.upsert_node(node)
        placer = PipelinedPlacer(tindex, [node], rng=random.Random(1),
                                 window=10)
        job = mock.job()
        job.TaskGroups[0].Tasks[0].Resources.CPU = 1000
        job.TaskGroups[0].Tasks[0].Resources.Networks = []
        # 6 evals x 1 placement x 1000 CPU on one 3900-CPU node: 3 fit.
        for _ in range(6):
            placer.submit(EvalRequest(job=job, tgs=[job.TaskGroups[0]]))
        results = placer.flush()
        placed = sum(int((r.chosen_rows >= 0).sum()) for r in results)
        assert placed == 3

    def test_matches_stack_semantics(self):
        from nomad_tpu.scheduler.pipeline import EvalRequest, PipelinedPlacer

        nodes = [mock.node() for _ in range(8)]
        tindex = TensorIndex()
        for n in nodes:
            tindex.nt.upsert_node(n)
        placer = PipelinedPlacer(tindex, nodes, rng=random.Random(1))
        job = mock.job()
        job.TaskGroups[0].Tasks[0].Resources.Networks = []
        placer.submit(EvalRequest(job=job, tgs=[job.TaskGroups[0]] * 8))
        (res,) = placer.flush()
        assert (res.chosen_rows >= 0).all()
        # Anti-affinity spreads over all 8 nodes.
        assert len(set(res.chosen_rows.tolist())) == 8


class TestSharding:
    def test_place_batch_sharded_8dev(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from nomad_tpu.parallel import place_batch_sharded, scheduling_mesh

        mesh = scheduling_mesh(jax.devices()[:8])
        n, p = 512, 16
        rng = np.random.default_rng(0)
        res = place_batch_sharded(
            mesh,
            rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
            rng.uniform(800, 3800, (n, 2)).astype(np.float32),
            np.zeros((n, 5), np.float32),
            np.ones((1, n), bool),
            np.zeros(n, np.int32),
            np.full((p, 5), 50, np.float32),
            np.zeros(p, np.int32),
            np.ones(p, bool),
            (rng.random(n) * 1e-3).astype(np.float32),
            np.float32(10.0),
            np.asarray(False),
            np.zeros(n, bool),
        )
        packed = np.asarray(res.packed)
        chosen = packed[:, 0].astype(np.int32)
        assert (chosen >= 0).all()
        assert len(set(chosen.tolist())) == p  # spread

    def test_sharded_matches_single_device(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        import jax.numpy as jnp

        from nomad_tpu.parallel import place_batch_sharded, scheduling_mesh
        from nomad_tpu.scheduler import kernels

        n, p = 256, 8
        rng = np.random.default_rng(3)
        args = [
            rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
            rng.uniform(800, 3800, (n, 2)).astype(np.float32),
            np.zeros((n, 5), np.float32),
            np.ones((1, n), bool),
            np.zeros(n, np.int32),
            np.full((p, 5), 50, np.float32),
            np.zeros(p, np.int32),
            np.ones(p, bool),
            (rng.random(n) * 1e-3).astype(np.float32),
            np.float32(10.0),
            np.asarray(False),
            np.zeros(n, bool),
        ]
        single = kernels.place_batch(*[jnp.asarray(a) for a in args])
        mesh = scheduling_mesh(jax.devices()[:8])
        sharded = place_batch_sharded(mesh, *args)
        np.testing.assert_array_equal(np.asarray(single.packed)[:, 0],
                                      np.asarray(sharded.packed)[:, 0])


class TestKeyedKernel:
    """The keyed-candidate kernel (kernels.place_batch_keyed) must be
    bit-identical to the monolithic scan kernels for every valid
    placement, single-device and sharded, with and without
    distinct_hosts and multi-eval resets. Exactness argument in the
    kernel's module comment; these tests are the empirical check."""

    def _inputs(self, n=512, t=3, seed=0):
        rng = np.random.default_rng(seed)
        return rng, dict(
            capacity=rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
            score_cap=rng.uniform(800, 3800, (n, 2)).astype(np.float32),
            usage=rng.uniform(0, 500, (n, 5)).astype(np.float32),
            tg_masks=rng.random((t, n)) < 0.9,
            job_counts=rng.integers(0, 3, n).astype(np.int32),
            key_demands=rng.uniform(10, 100, (t, 5)).astype(np.float32),
            noise=(rng.random(n) * 1e-3).astype(np.float32),
            banned0=rng.random(n) < 0.05,
        )

    @pytest.mark.parametrize(
        "p,n_valid,distinct,multi",
        [(64, 61, False, False), (64, 64, True, False),
         (128, 128, False, True), (256, 250, True, True),
         (8, 5, False, False)])
    def test_bit_identical_to_monolithic(self, p, n_valid, distinct, multi):
        import jax

        from nomad_tpu.parallel import scheduling_mesh
        from nomad_tpu.scheduler import kernels

        rng, d = self._inputs()
        t = d["key_demands"].shape[0]
        tg_ids = rng.integers(0, t, p).astype(np.int32)
        valid = np.zeros(p, bool)
        valid[:n_valid] = True
        demands = d["key_demands"][tg_ids] * valid[:, None]
        reset = np.zeros(p, bool)
        if multi:
            reset[::8] = True
        dd = np.asarray(distinct)
        if multi:
            ref = kernels.place_batch_multi(
                d["capacity"], d["score_cap"], d["usage"], d["tg_masks"],
                d["job_counts"], demands, tg_ids, valid, d["noise"],
                np.float32(10.0), dd, d["banned0"], reset)
        else:
            ref = kernels.place_batch(
                d["capacity"], d["score_cap"], d["usage"], d["tg_masks"],
                d["job_counts"], demands, tg_ids, valid, d["noise"],
                np.float32(10.0), dd, d["banned0"])
        meshes = [None]
        if len(jax.devices()) >= 8:
            meshes.append(scheduling_mesh(jax.devices()[:8]))
        for mesh in meshes:
            res = kernels.place_batch_keyed(
                mesh, d["capacity"], d["score_cap"], d["usage"],
                d["tg_masks"], d["job_counts"], d["key_demands"], tg_ids,
                valid, d["noise"], np.float32(10.0), dd, d["banned0"],
                reset, n_valid)
            rp = np.asarray(ref.packed)
            bp = np.asarray(res.packed)
            np.testing.assert_array_equal(rp[valid], bp[valid])
            # Padding placements: chosen/score contract holds (n_feasible
            # is unspecified there — no consumer reads it).
            assert (bp[~valid, 0] == -1).all()
            assert np.isneginf(bp[~valid, 1]).all()
            np.testing.assert_array_equal(np.asarray(ref.usage_after),
                                          np.asarray(res.usage_after))

    def test_compaction_survives_starved_key_with_duplicates(self):
        """Regression: a key with almost no feasible rows pads its trim
        slots with -inf entries that can be another key's duplicate
        candidate copies; the compaction dedup must rebuild
        first-occurrence from scratch (identical copies are
        interchangeable) instead of carrying the pre-trim keep mask, or
        rows vanish from the feasible table."""
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from nomad_tpu.parallel import scheduling_mesh
        from nomad_tpu.scheduler import kernels

        n, t, p = 512, 2, 64
        rng = np.random.default_rng(9)
        d = dict(
            capacity=rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
            score_cap=rng.uniform(800, 3800, (n, 2)).astype(np.float32),
            usage=rng.uniform(0, 300, (n, 5)).astype(np.float32),
            job_counts=np.zeros(n, np.int32),
            noise=(rng.random(n) * 1e-3).astype(np.float32),
            banned0=np.zeros(n, bool),
        )
        # Key 0 is eligible on 2 rows only (every shard's top-k for it is
        # mostly -inf padding); key 1 is eligible broadly. With 8 shards
        # of 64 rows and a 64-candidate budget, every row appears in both
        # keys' local candidate sets, so duplicates are guaranteed and
        # compaction (2*64 < 1024) is active.
        tg_masks = np.zeros((t, n), bool)
        tg_masks[0, [3, 200]] = True
        tg_masks[1] = rng.random(n) < 0.95
        kd = np.array([[30, 40, 0, 0, 0], [20, 25, 0, 0, 0]], np.float32)
        tg_ids = np.asarray([0] * 4 + [1] * 60, np.int32)
        valid = np.ones(p, bool)
        demands = kd[tg_ids]
        reset = np.zeros(p, bool)
        ref = kernels.place_batch(
            d["capacity"], d["score_cap"], d["usage"], tg_masks,
            d["job_counts"], demands, tg_ids, valid, d["noise"],
            np.float32(10.0), np.asarray(False), d["banned0"])
        one = kernels.place_batch_keyed(
            None, d["capacity"], d["score_cap"], d["usage"], tg_masks,
            d["job_counts"], kd, tg_ids, valid, d["noise"],
            np.float32(10.0), np.asarray(False), d["banned0"], reset, p)
        mesh = scheduling_mesh(jax.devices()[:8])
        res = kernels.place_batch_keyed(
            mesh, d["capacity"], d["score_cap"], d["usage"], tg_masks,
            d["job_counts"], kd, tg_ids, valid, d["noise"],
            np.float32(10.0), np.asarray(False), d["banned0"], reset, p)
        rp = np.asarray(ref.packed)
        mp = np.asarray(res.packed)
        # The regression under test is candidate SELECTION: a dropped row
        # would flip a chosen index or an n_feasible count. Those (and
        # the chained usage) must match the monolithic scan exactly.
        np.testing.assert_array_equal(rp[:, 0], mp[:, 0])
        np.testing.assert_array_equal(rp[:, 2], mp[:, 2])
        np.testing.assert_array_equal(np.asarray(ref.usage_after),
                                      np.asarray(res.usage_after))
        # Scores: <= 2 ulp vs the scan on XLA:CPU. Environmental, not a
        # selection bug — the replay and the scan are two differently
        # fused compilations of the same f32 ops (`- counts * penalty
        # + noise` may or may not FMA-contract per fusion shape), and
        # this shape's data lands on a boundary (observed: one score of
        # 64 off by ~1e-6, chosen rows and usage bit-identical; the
        # same codegen class as the historical keyed-vs-scan seed
        # failures). On TPU both programs round identically.
        np.testing.assert_array_almost_equal_nulp(
            np.where(np.isfinite(rp[:, 1]), rp[:, 1], 0.0),
            np.where(np.isfinite(mp[:, 1]), mp[:, 1], 0.0), nulp=2)
        # The ISSUE-12 parity bar is exact: the sharded pipeline must
        # match the SINGLE-DEVICE keyed kernel bit-for-bit.
        np.testing.assert_array_equal(np.asarray(one.packed), mp)

    def test_sharded_collective_count_is_per_window(self):
        """The point of the shard-local mesh pipeline: NO compiled
        program contains a collective. The cold stage scores and top-Ks
        only its own shard's rows (shard_map, no cross-shard ops), the
        winner-row exchange is an explicit device_put — a point-to-point
        transfer, not a rendezvous collective — and warm windows run
        entirely on the lead device. The naive SPMD scan pays 2
        collectives PER PLACEMENT inside its scan body; the old
        single-program keyed variant paid 2 per window. Now: zero."""
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from nomad_tpu.parallel import scheduling_mesh
        from nomad_tpu.scheduler import kernels

        mesh = scheduling_mesh(jax.devices()[:8])
        p = 64
        counts = kernels.mesh_collective_audit(
            mesh, kernels.keyed_cand_count(p), n_rows=512,
            n_keys=self._inputs()[1]["key_demands"].shape[0], p_pad=p)
        assert counts["cold"] == 0, counts
        assert counts["pool_build"] == 0, counts
        assert counts["warm"] == 0, counts
        assert counts["apply"] == 0, counts


class TestPlacementQualityParity:
    def test_tpu_at_least_as_good_as_reference_algorithm(self):
        """Global argmax must reach >= the reference iterator chain's total
        bin-pack score on the same workload."""
        from nomad_tpu.scheduler.cpu_reference import CPUReferenceStack
        from nomad_tpu.scheduler.pipeline import EvalRequest, PipelinedPlacer

        nodes = []
        rng = np.random.default_rng(11)
        for i in range(50):
            n = mock.node()
            # Heterogeneous capacity so scores differ meaningfully.
            n.Resources.CPU = int(rng.integers(2000, 8000))
            n.Resources.MemoryMB = int(rng.integers(4096, 16384))
            compute_node_class(n)
            nodes.append(n)

        job = mock.job()
        job.TaskGroups[0].Tasks[0].Resources.Networks = []
        tgs = [job.TaskGroups[0]] * 20

        tindex = TensorIndex()
        for n in nodes:
            tindex.nt.upsert_node(n)
        placer = PipelinedPlacer(tindex, nodes, rng=random.Random(5))
        placer.submit(EvalRequest(job=job, tgs=tgs))
        (res,) = placer.flush()
        tpu_scores = res.scores[res.chosen_rows >= 0]
        # Remove the tie-break noise contribution before comparing.
        tpu_total = float(tpu_scores.sum()) - 1e-3 * len(tpu_scores)

        ref = CPUReferenceStack(nodes, rng=random.Random(5))
        ref.set_job(job)
        ref_results = [r for r in ref.select_batch(tgs) if r is not None]
        ref_total = sum(s for _, s in ref_results)

        assert len(tpu_scores) >= len(ref_results)
        assert tpu_total >= ref_total - 1e-3


class TestHostKernelParity:
    """place_batch_host is the numpy mirror used for shallow windows (a
    device readback costs a fixed ~100ms sync on remote-attached TPUs);
    its placements must match the device kernel exactly on the same
    inputs (same f32 BestFit-v3 + Inf/NaN edges, same anti-affinity and
    noise tie-break, same in-loop usage chaining)."""

    def _inputs(self, seed, n=256, p=48, t=8):
        import numpy.random as nr

        rng = nr.default_rng(seed)
        capacity = rng.uniform(100, 4000, (n, 8)).astype(np.float32)
        usage = (capacity * rng.uniform(0, 0.9, (n, 8))).astype(np.float32)
        score_cap = capacity[:, :2] * rng.uniform(
            0.5, 1.0, (n, 2)).astype(np.float32)
        tg_masks = rng.random((t, n)) < 0.7
        job_counts = rng.integers(0, 3, n).astype(np.int32)
        demands = rng.uniform(1, 500, (p, 8)).astype(np.float32)
        tg_ids = rng.integers(0, t, p).astype(np.int32)
        valid = rng.random(p) < 0.9
        noise = (rng.random(n) * 1e-3).astype(np.float32)
        banned = rng.random(n) < 0.05
        return (capacity, score_cap, usage, tg_masks, job_counts, demands,
                tg_ids, valid, noise, np.float32(10.0), True, banned)

    @pytest.mark.parametrize("seed", [0, 7, 42])
    def test_host_matches_device(self, seed):
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        args = self._inputs(seed)
        dev = kernels.place_batch(*[jnp.asarray(a) for a in args])
        host = kernels.place_batch_host(*args)
        dev_packed = np.asarray(dev.packed)
        # Same placement decisions row-for-row.
        np.testing.assert_array_equal(dev_packed[:, 0], host.packed[:, 0])
        np.testing.assert_array_equal(dev_packed[:, 2], host.packed[:, 2])
        # Scores agree to f32 tolerance (TPU transcendental approximations
        # may differ in the last ulps from host libm).
        finite = np.isfinite(dev_packed[:, 1])
        np.testing.assert_array_equal(finite, np.isfinite(host.packed[:, 1]))
        np.testing.assert_allclose(dev_packed[finite, 1],
                                   host.packed[finite, 1],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(dev.usage_after),
                                   host.usage_after, rtol=1e-5, atol=1e-3)

    def test_distinct_hosts_off(self):
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        args = list(self._inputs(3))
        args[10] = False  # distinct_hosts off: banned must be ignored
        dev = kernels.place_batch(*[jnp.asarray(a) for a in args])
        host = kernels.place_batch_host(*args)
        np.testing.assert_array_equal(
            np.asarray(dev.packed)[:, 0], host.packed[:, 0])


class TestMultiKernelParity:
    """place_batch_multi fuses a window of same-shaped evals into one scan
    with per-eval resets of the job-local state; its placements must be
    IDENTICAL to dispatching place_batch per eval chained on usage."""

    def test_multi_matches_sequential_chain(self):
        import jax
        import jax.numpy as jnp

        from nomad_tpu.scheduler import kernels

        rng = np.random.default_rng(11)
        n, p, t, evals = 256, 16, 3, 5
        capacity = rng.uniform(500, 3000, (n, 8)).astype(np.float32)
        score_cap = capacity[:, :2].copy()
        usage0 = (capacity * rng.uniform(0, 0.5, (n, 8))).astype(np.float32)
        tg_masks = rng.random((t, n)) < 0.8
        jc0 = np.zeros(n, np.int32)
        demands = rng.uniform(1, 200, (p, 8)).astype(np.float32)
        tg_ids = rng.integers(0, t, p).astype(np.int32)
        valid = np.ones(p, bool)
        noise = (rng.random(n) * 1e-3).astype(np.float32)
        banned0 = np.zeros(n, bool)

        # Sequential per-eval chain.
        usage = jnp.asarray(usage0)
        seq_packed = []
        for _ in range(evals):
            res = kernels.place_batch(
                jnp.asarray(capacity), jnp.asarray(score_cap), usage,
                jnp.asarray(tg_masks), jnp.asarray(jc0),
                jnp.asarray(demands), jnp.asarray(tg_ids),
                jnp.asarray(valid), jnp.asarray(noise), jnp.float32(10.0),
                jnp.asarray(True), jnp.asarray(banned0))
            seq_packed.append(np.asarray(res.packed))
            usage = res.usage_after
        seq_usage = np.asarray(usage)

        # One multi kernel over the same five evals.
        reset = np.zeros(evals * p, bool)
        reset[::p] = True
        multi = kernels.place_batch_multi(
            jnp.asarray(capacity), jnp.asarray(score_cap),
            jnp.asarray(usage0), jnp.asarray(tg_masks), jnp.asarray(jc0),
            jnp.asarray(np.tile(demands, (evals, 1))),
            jnp.asarray(np.tile(tg_ids, evals)),
            jnp.asarray(np.tile(valid, evals)), jnp.asarray(noise),
            jnp.float32(10.0), jnp.asarray(True), jnp.asarray(banned0),
            jnp.asarray(reset))
        multi_packed = np.asarray(multi.packed)
        for e in range(evals):
            np.testing.assert_array_equal(
                multi_packed[e * p:(e + 1) * p], seq_packed[e],
                err_msg=f"eval {e} diverged")
        np.testing.assert_allclose(np.asarray(multi.usage_after),
                                   seq_usage, rtol=1e-6, atol=1e-3)
