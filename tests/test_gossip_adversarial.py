"""Adversarial gossip tests: SWIM's invariants under loss, partitions,
false accusation, incarnation races, and churn.

The reference gets these properties from vendored hashicorp/memberlist;
a from-scratch SWIM must prove them. The fault-injection seam is
Memberlist.transport_filter (drops UDP sends and anti-entropy dials), which
models lossy links and asymmetric partitions deterministically.
"""

import random
import threading
import time

import msgpack
import pytest

from nomad_tpu.gossip import (
    ALIVE,
    DEAD,
    GossipConfig,
    Memberlist,
)
from nomad_tpu.gossip.memberlist import _ALIVE, _SUSPECT, SUSPECT

from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # networked timing suite: one retry


def make(name, events=None, tags=None, cfg=None):
    cb = None
    if events is not None:
        cb = lambda ev, m: events.append((ev, m.name))
    ml = Memberlist(name, tags=tags or {}, config=cfg or GossipConfig.fast(),
                    on_event=cb)
    ml.start()
    return ml


def build_cluster(names, cfg=None):
    mls = [make(names[0], cfg=cfg)]
    for name in names[1:]:
        m = make(name, cfg=cfg)
        assert m.join([f"{mls[0].addr}:{mls[0].port}"]) == 1
        mls.append(m)
    for m in mls:
        wait_for(lambda m=m: m.num_alive() == len(names),
                 msg=f"{m.name} converged")
    return mls


def states(ml):
    return {m.name: m.state for m in ml.members()}


class TestLossyLinks:
    def test_cluster_survives_sustained_packet_loss(self):
        """25% loss on every link: members may transiently be suspected but
        refutation keeps every live member from being declared dead, and
        after the loss clears the cluster re-converges fully alive."""
        names = ["n%d" % i for i in range(5)]
        mls = build_cluster(names)
        try:
            rng = random.Random(42)
            for m in mls:
                m.transport_filter = lambda dest, msgs: rng.random() > 0.25
            # Several full suspicion cycles under loss.
            time.sleep(2.0)
            for m in mls:
                m.transport_filter = None
            # Everyone re-converges: all 5 alive at every member (suspects
            # refute; no permanent death of a live node).
            for m in mls:
                wait_for(lambda m=m: all(
                    x.state == ALIVE for x in m.members()),
                    timeout=20, msg=f"{m.name} all-alive after loss")
                assert m.num_alive() == 5
        finally:
            for m in mls:
                m.shutdown()

    def test_refutation_under_sustained_false_accusation(self):
        """An attacker floods SUSPECT(victim) at everyone: the victim must
        keep out-incarnating the accusations and never be declared dead."""
        names = ["a", "b", "c", "d"]
        mls = build_cluster(names)
        victim = mls[1]
        try:
            stop = threading.Event()

            def accuse():
                import socket

                sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                while not stop.is_set():
                    inc = victim.local_member().incarnation
                    pkt = msgpack.packb(
                        [(_SUSPECT, "b", inc, "a")], use_bin_type=True)
                    for m in mls:
                        if m.name != "b":
                            sock.sendto(pkt, (m.addr, m.port))
                    time.sleep(0.05)

            t = threading.Thread(target=accuse, daemon=True)
            t.start()
            inc_before = victim.local_member().incarnation
            time.sleep(2.0)  # ~7 suspicion timeouts under constant attack
            stop.set()
            t.join()
            # The victim refuted (incarnation climbed) and nobody ever
            # committed the death.
            assert victim.local_member().incarnation > inc_before
            for m in mls:
                assert states(m)["b"] in (ALIVE, SUSPECT), states(m)
            for m in mls:
                wait_for(lambda m=m: states(m)["b"] == ALIVE,
                         timeout=10, msg=f"{m.name} sees b alive")
        finally:
            for m in mls:
                m.shutdown()


class TestIncarnationRaces:
    def test_concurrent_suspect_and_alive_converge_to_newest(self):
        """A SUSPECT(inc=k) racing an ALIVE(inc=k+1) through different
        members must converge to alive everywhere — incarnation order wins,
        not arrival order."""
        names = ["a", "b", "c", "d"]
        mls = build_cluster(names)
        a, b, c, d = mls
        try:
            import socket

            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            inc = b.local_member().incarnation
            member_b = b.local_member()
            # c hears the stale suspicion; d hears the newer alive; both
            # gossip their view onward.
            sock.sendto(msgpack.packb([(_SUSPECT, "b", inc, "a")],
                                      use_bin_type=True), (c.addr, c.port))
            sock.sendto(msgpack.packb(
                [(_ALIVE, "b", member_b.addr, member_b.port, inc + 1, {})],
                use_bin_type=True), (d.addr, d.port))
            for m in (a, c, d):
                wait_for(lambda m=m: states(m)["b"] == ALIVE
                         and next(x for x in m.members()
                                  if x.name == "b").incarnation >= inc + 1,
                         timeout=10,
                         msg=f"{m.name} converges to alive@inc+1")
        finally:
            for m in mls:
                m.shutdown()

    def test_stale_suspect_after_refutation_is_ignored(self):
        """A suspicion carrying an incarnation older than the member's
        current one must be dropped on arrival."""
        mls = build_cluster(["a", "b", "c"])
        a, b, c = mls
        try:
            import socket

            inc = b.local_member().incarnation
            # b refutes pre-emptively (tag update bumps incarnation).
            b.set_tags({"x": "1"})
            wait_for(lambda: next(m for m in a.members()
                                  if m.name == "b").incarnation > inc,
                     msg="a sees b's new incarnation")
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.sendto(msgpack.packb([(_SUSPECT, "b", inc, "c")],
                                      use_bin_type=True), (a.addr, a.port))
            time.sleep(0.3)
            assert states(a)["b"] == ALIVE
        finally:
            for m in mls:
                m.shutdown()


class TestAsymmetricPartition:
    def test_one_way_link_does_not_kill_a_reachable_member(self):
        """b's packets to a are dropped (one-way break) but b<->c and a<->c
        work: a may suspect b (pings unacked) but the suspicion must be
        refuted through c — b is never declared dead anywhere."""
        mls = build_cluster(["a", "b", "c"])
        a, b, c = mls
        try:
            blocked = (a.addr, a.port)
            b.transport_filter = lambda dest, msgs: dest != blocked
            time.sleep(2.0)  # many probe rounds with the broken link
            for m in mls:
                assert states(m)["b"] != DEAD, (m.name, states(m))
            b.transport_filter = None
            for m in mls:
                wait_for(lambda m=m: states(m)["b"] == ALIVE,
                         timeout=10, msg=f"{m.name} sees b alive")
        finally:
            for m in mls:
                m.shutdown()

    def test_fully_isolated_member_dies_and_rejoins(self):
        """b loses ALL outbound links: the cluster declares it dead within
        the suspicion bound; when the partition heals, b rejoins and every
        view re-converges."""
        mls = build_cluster(["a", "b", "c", "d"])
        a, b, c, d = mls
        try:
            b.transport_filter = lambda dest, msgs: False
            # Inbound to b still works, but no acks/refutations escape.
            for m in (a, c, d):
                wait_for(lambda m=m: states(m)["b"] == DEAD,
                         timeout=20, msg=f"{m.name} declares b dead")
            b.transport_filter = None
            # b re-announces (its own probes/gossip resume; push-pull
            # heals the rest).
            assert b.join([f"{a.addr}:{a.port}"]) == 1
            for m in mls:
                wait_for(lambda m=m: all(x.state == ALIVE
                                         for x in m.members()),
                         timeout=20, msg=f"{m.name} healed")
        finally:
            for m in mls:
                m.shutdown()


class TestChurn:
    def test_ten_member_churn_converges(self):
        """10 members; 3 crash (no leave). The 7 survivors converge on
        exactly 7 alive within the suspicion bound, then 3 new members join
        and every survivor converges on 10 alive."""
        names = ["m%d" % i for i in range(10)]
        mls = build_cluster(names)
        try:
            crashed = {"m3", "m6", "m9"}
            for m in mls:
                if m.name in crashed:
                    m.shutdown()
            live = [m for m in mls if m.name not in crashed]
            for m in live:
                wait_for(lambda m=m: m.num_alive() == 7,
                         timeout=30, msg=f"{m.name} sees 7 after crashes")
                assert all(states(m)[n] == DEAD for n in crashed)
            newcomers = []
            for name in ("x0", "x1", "x2"):
                nm = make(name)
                newcomers.append(nm)
                assert nm.join([f"{live[0].addr}:{live[0].port}"]) == 1
            mls.extend(newcomers)
            for m in live + newcomers:
                wait_for(lambda m=m: m.num_alive() == 10,
                         timeout=30, msg=f"{m.name} sees 10 after joins")
        finally:
            for m in mls:
                m.shutdown()

    def test_piggyback_budget_starvation_still_disseminates(self):
        """A burst of simultaneous state changes (several tag updates racing
        a death) exceeds one packet's piggyback budget; retransmission must
        still deliver every update."""
        names = ["p%d" % i for i in range(8)]
        mls = build_cluster(names)
        try:
            # 6 members change tags at once + one crashes: 7 broadcasts
            # compete for the 6-message piggyback budget.
            for i, m in enumerate(mls[:6]):
                m.set_tags({"v": str(i)})
            mls[7].shutdown()
            survivors = mls[:7]
            for m in survivors:
                wait_for(lambda m=m: states(m)["p7"] == DEAD,
                         timeout=30, msg=f"{m.name} sees the crash")
                for i in range(6):
                    wait_for(lambda m=m, i=i: next(
                        (x for x in m.members() if x.name == f"p{i}"),
                        None) is not None and next(
                        x for x in m.members()
                        if x.name == f"p{i}").tags.get("v") == str(i),
                        timeout=30, msg=f"{m.name} sees p{i} tags")
        finally:
            for m in mls:
                m.shutdown()
