"""HCL jobspec parser grid (reference: jobspec/parse_test.go — the full
fixture matrix: every block type, constraint sugar, strict keys, duration
coercion, defaults)."""

import pytest

from nomad_tpu.jobspec import parse_job
from nomad_tpu.jobspec.parse import JobSpecError
from nomad_tpu.structs.structs import (
    HOUR,
    MINUTE,
    SECOND,
    ConstraintDistinctHosts,
    ConstraintRegex,
    ConstraintVersion,
)

FULL = '''
job "binstore" {
  region = "fringe"
  type = "service"
  priority = 52
  all_at_once = true
  datacenters = ["us2", "eu1"]

  meta {
    foo = "bar"
  }

  constraint {
    attribute = "${attr.kernel.os}"
    value = "windows"
  }

  update {
    stagger = "60s"
    max_parallel = 2
  }

  group "binsl" {
    count = 5

    restart {
      attempts = 5
      interval = "10m"
      delay = "15s"
      mode = "delay"
    }

    constraint {
      attribute = "${attr.kernel.os}"
      value = "linux"
    }

    meta {
      elb_checks = "3"
    }

    task "binstore" {
      driver = "docker"
      user = "bob"

      config {
        image = "hashicorp/binstore"
      }

      env {
        HELLO = "world"
        LOREM = "ipsum"
      }

      service {
        name = "binstore-check"
        tags = ["foo", "bar"]
        port = "http"
        check {
          name = "check-name"
          type = "tcp"
          interval = "10s"
          timeout = "2s"
        }
      }

      resources {
        cpu = 500
        memory = 128
        network {
          mbits = 100
          port "http" {}
          port "https" {}
          port "admin" {
            static = 8080
          }
        }
      }

      kill_timeout = "22s"

      logs {
        max_files = 10
        max_file_size = 100
      }

      artifact {
        source = "http://foo.com/artifact"
        destination = "local/"
      }
    }
  }
}
'''


class TestFullJob:
    def test_every_block(self):
        job = parse_job(FULL)
        assert job.ID == "binstore" and job.Region == "fringe"
        assert job.Priority == 52 and job.AllAtOnce
        assert job.Datacenters == ["us2", "eu1"]
        assert job.Meta == {"foo": "bar"}
        assert job.Constraints[0].LTarget == "${attr.kernel.os}"
        assert job.Constraints[0].RTarget == "windows"
        assert job.Update.Stagger == 60 * SECOND
        assert job.Update.MaxParallel == 2

        tg = job.TaskGroups[0]
        assert tg.Name == "binsl" and tg.Count == 5
        assert tg.RestartPolicy.Attempts == 5
        assert tg.RestartPolicy.Interval == 10 * MINUTE
        assert tg.RestartPolicy.Delay == 15 * SECOND
        assert tg.Meta == {"elb_checks": "3"}

        task = tg.Tasks[0]
        assert task.Driver == "docker" and task.User == "bob"
        assert task.Config["image"] == "hashicorp/binstore"
        assert task.Env == {"HELLO": "world", "LOREM": "ipsum"}
        assert task.KillTimeout == 22 * SECOND
        assert task.LogConfig.MaxFiles == 10
        assert task.LogConfig.MaxFileSizeMB == 100
        assert task.Artifacts[0].GetterSource == "http://foo.com/artifact"

        svc = task.Services[0]
        assert svc.Name == "binstore-check"
        assert svc.Tags == ["foo", "bar"] and svc.PortLabel == "http"
        check = svc.Checks[0]
        assert check.Type == "tcp" and check.Interval == 10 * SECOND

        net = task.Resources.Networks[0]
        assert net.MBits == 100
        assert {p.Label for p in net.DynamicPorts} == {"http", "https"}
        assert {(p.Label, p.Value) for p in net.ReservedPorts} == \
            {("admin", 8080)}


class TestConstraintSugar:
    def _one(self, block):
        job = parse_job('job "x" { %s group "g" { task "t" { '
                        'driver = "raw_exec" } } }' % block)
        return job.Constraints[0]

    def test_version_sugar(self):
        c = self._one('constraint { attribute = "${attr.nomad.version}" '
                      'version = ">= 0.4" }')
        assert c.Operand == ConstraintVersion and c.RTarget == ">= 0.4"

    def test_regexp_sugar(self):
        c = self._one('constraint { attribute = "${attr.arch}" '
                      'regexp = "x86.*" }')
        assert c.Operand == ConstraintRegex and c.RTarget == "x86.*"

    def test_distinct_hosts_sugar(self):
        c = self._one("constraint { distinct_hosts = true }")
        assert c.Operand == ConstraintDistinctHosts


class TestStrictness:
    def test_unknown_job_key_rejected(self):
        with pytest.raises(JobSpecError, match="invalid key"):
            parse_job('job "x" { bogus = 1 group "g" { task "t" { '
                      'driver = "raw_exec" } } }')

    def test_unknown_task_key_rejected(self):
        with pytest.raises(JobSpecError, match="invalid key"):
            parse_job('job "x" { group "g" { task "t" { '
                      'driver = "raw_exec" nonsense = true } } }')

    def test_missing_job_block(self):
        with pytest.raises(JobSpecError, match="'job' block not found"):
            parse_job('group "g" {}')

    def test_two_job_blocks_rejected(self):
        with pytest.raises(JobSpecError):
            parse_job('job "a" { } job "b" { }')


class TestDefaults:
    def test_bare_task_gets_defaults(self):
        job = parse_job('job "x" { group "g" { task "t" { '
                        'driver = "raw_exec" } } }')
        task = job.TaskGroups[0].Tasks[0]
        assert task.Resources is not None and task.Resources.CPU > 0
        assert task.LogConfig is not None
        assert job.Type == "service"
        assert job.TaskGroups[0].Count == 1

    def test_task_outside_group_gets_wrapped(self):
        """A job-level task is wrapped in a group of the same name
        (reference: parse.go's implicit group)."""
        job = parse_job('job "x" { task "solo" { driver = "raw_exec" } }')
        assert len(job.TaskGroups) == 1
        assert job.TaskGroups[0].Name == "solo"
        assert job.TaskGroups[0].Tasks[0].Name == "solo"

    def test_periodic_block(self):
        job = parse_job('job "x" { type = "batch" '
                        'periodic { cron = "*/5 * * * *" '
                        'prohibit_overlap = true } '
                        'group "g" { task "t" { driver = "raw_exec" } } }')
        assert job.Periodic is not None
        assert job.Periodic.Spec == "*/5 * * * *"
        assert job.Periodic.ProhibitOverlap is True
        assert job.is_periodic()
