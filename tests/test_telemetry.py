"""Telemetry tests: sink aggregation, statsd datagrams, and counters
advancing through a real scheduling cycle (reference shapes: go-metrics
inmem/statsd behavior; EmitStats gauges of eval_broker.go:650-662)."""

import pytest

import socket
import time

from nomad_tpu import mock, telemetry
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete
from nomad_tpu.telemetry.metrics import InMemSink, MetricsRegistry, StatsdSink


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # real timers/sockets: one retry

class TestInMemSink:
    def test_gauge_keeps_last_value(self):
        sink = InMemSink(interval=60.0)
        sink.set_gauge(("a", "b"), 1.0)
        sink.set_gauge(("a", "b"), 5.0)
        snap = sink.snapshot()
        assert snap["Gauges"] == [{"Name": "a.b", "Value": 5.0}]

    def test_samples_aggregate(self):
        sink = InMemSink(interval=60.0)
        for v in (10.0, 20.0, 30.0):
            sink.add_sample(("lat",), v)
        [s] = sink.snapshot()["Samples"]
        assert s["Count"] == 3
        assert s["Sum"] == 60.0
        assert s["Min"] == 10.0 and s["Max"] == 30.0
        assert abs(s["Mean"] - 20.0) < 1e-9

    def test_counters_aggregate(self):
        sink = InMemSink(interval=60.0)
        sink.incr_counter(("hits",), 1)
        sink.incr_counter(("hits",), 1)
        [c] = sink.snapshot()["Counters"]
        assert c["Count"] == 2 and c["Sum"] == 2.0

    def test_interval_rotation_bounded(self):
        sink = InMemSink(interval=1.0, retain=3)
        for i in range(10):
            with sink._lock:
                sink._current(1000.0 + i)  # each stamp its own interval
        assert len(sink._intervals) <= 3

    def test_interval_floored_to_one_second(self):
        # 0 would divide-by-zero inside the swallow-all sink fan-out and
        # silently blank telemetry; sub-second fragments every sample.
        assert InMemSink(interval=0).interval == 1.0
        assert InMemSink(interval=0.001).interval == 1.0

    def test_interval_rollover_starts_fresh_and_retains_past(self):
        """Crossing an interval boundary opens a NEW aggregation window
        (snapshot shows only the current one) while the previous interval
        stays retained for the dump/debug surfaces."""
        sink = InMemSink(interval=10.0, retain=5)
        with sink._lock:
            cur = sink._current(1000.0)
        cur["counters"]["hits"] = object()
        with sink._lock:
            nxt = sink._current(1011.0)  # next 10s bucket
        assert nxt is not cur
        assert nxt["counters"] == {}
        assert len(sink._intervals) == 2
        assert sink._intervals[0]["start"] == 1000.0
        assert sink._intervals[1]["start"] == 1010.0


class TestStatsdSink:
    def test_datagrams_cross_the_socket(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(2.0)
        addr = "127.0.0.1:%d" % recv.getsockname()[1]
        sink = StatsdSink(addr)
        sink.set_gauge(("nomad", "broker", "total_ready"), 4)
        sink.incr_counter(("nomad", "rpc", "request"), 1)
        sink.add_sample(("nomad", "fsm", "register_job"), 1.25)
        got = set()
        for _ in range(3):
            got.add(recv.recv(1024).decode())
        assert "nomad.broker.total_ready:4|g" in got
        assert "nomad.rpc.request:1|c" in got
        assert "nomad.fsm.register_job:1.25|ms" in got
        sink.close()
        recv.close()


class TestRegistry:
    def test_measure_records_milliseconds(self):
        reg = MetricsRegistry()
        with reg.measure(("op",)):
            time.sleep(0.01)
        [s] = reg.snapshot()["Samples"]
        assert s["Name"] == "op"
        assert s["Min"] >= 5.0  # ms, not seconds

    def test_broken_sink_never_breaks_caller(self):
        reg = MetricsRegistry()

        class Bad:
            def set_gauge(self, k, v):
                raise RuntimeError("boom")

        reg.add_sink(Bad())
        reg.set_gauge(("g",), 1)  # must not raise
        assert reg.snapshot()["Gauges"][0]["Value"] == 1

    def test_reconfigure_closes_replaced_statsd_sink(self):
        """SIGHUP reloads swap the sink list; the replaced StatsdSink's
        UDP socket must be closed, not leaked (one socket per reload)."""
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        addr = "127.0.0.1:%d" % recv.getsockname()[1]
        try:
            reg = MetricsRegistry()
            reg.configure(statsd_addr=addr)
            old = next(s for s in reg._sinks
                       if isinstance(s, StatsdSink))
            reg.configure(statsd_addr=addr)
            new = next(s for s in reg._sinks
                       if isinstance(s, StatsdSink))
            assert new is not old
            assert old._sock.fileno() == -1, "replaced sink not closed"
            assert new._sock.fileno() != -1
            new.close()
        finally:
            recv.close()

    def test_unresolvable_statsd_addr_degrades_not_raises(self):
        """A bad statsd target must not abort agent boot/reload: warn and
        keep the in-memory sink."""
        reg = MetricsRegistry()
        reg.configure(statsd_addr="no-such-host.invalid:8125")
        assert not any(isinstance(s, StatsdSink) for s in reg._sinks)
        reg.set_gauge(("still", "working"), 1.0)
        assert reg.snapshot()["Gauges"][0]["Value"] == 1.0

    def test_fan_survives_concurrent_reconfigure(self):
        """_fan snapshots the sink-list reference under the lock; a storm
        of configure() swaps racing a storm of writes must neither raise
        nor blank telemetry."""
        import threading

        reg = MetricsRegistry()
        stop = threading.Event()
        errors = []

        def reconfigure():
            while not stop.is_set():
                try:
                    reg.configure(collection_interval=60.0)
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

        t = threading.Thread(target=reconfigure, daemon=True)
        t.start()
        try:
            for i in range(2000):
                reg.incr_counter(("race", "hits"))
        finally:
            stop.set()
            t.join(5.0)
        assert not errors


class TestTelemetryDumpHandler:
    def test_sigusr1_dump_logs_valid_snapshot_json(self, caplog):
        """The SIGUSR1 handler (cli/commands.py dump_telemetry) dumps the
        in-memory snapshot as one parseable JSON log line."""
        import json
        import logging

        from nomad_tpu.cli.commands import dump_telemetry

        telemetry.configure(collection_interval=3600.0)
        telemetry.incr_counter(("dump", "probe"))
        with caplog.at_level(logging.INFO, logger="nomad.agent"):
            dump_telemetry()  # signature-compatible with signal delivery
        [record] = [r for r in caplog.records
                    if "metrics snapshot" in r.getMessage()]
        payload = json.loads(record.getMessage().split(":", 1)[1])
        assert set(payload) == {"Timestamp", "Gauges", "Counters",
                                "Samples"}
        assert any(c["Name"] == "dump.probe"
                   for c in payload["Counters"])


class TestSchedulingCycleMetrics:
    def test_counters_advance_through_a_cycle(self):
        """One job register -> schedule -> commit cycle must leave FSM
        apply timers, plan evaluate/apply timers, and broker gauges in the
        global registry (reference: fsm.go:147, plan_apply.go:168,195,
        eval_broker.go:650)."""
        # Fresh in-mem sink with a huge interval: counts cannot rotate away
        # mid-test and earlier tests' noise is discarded.
        telemetry.configure(collection_interval=3600.0)
        before = telemetry.snapshot()

        def sample_count(snap, name):
            for s in snap["Samples"]:
                if s["Name"] == name:
                    return s["Count"]
            return 0

        srv = Server(ServerConfig(num_schedulers=1, dev_mode=True))
        try:
            srv.establish_leadership()
            for _ in range(2):
                srv.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: (
                (e := srv.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete))
            srv._emit_stats()
            snap = telemetry.snapshot()
            assert sample_count(snap, "nomad.fsm.register_job") \
                > sample_count(before, "nomad.fsm.register_job")
            assert sample_count(snap, "nomad.fsm.register_node") \
                > sample_count(before, "nomad.fsm.register_node")
            assert sample_count(snap, "nomad.plan.evaluate") \
                > sample_count(before, "nomad.plan.evaluate")
            assert sample_count(snap, "nomad.plan.apply") \
                > sample_count(before, "nomad.plan.apply")
            gauges = {g["Name"] for g in snap["Gauges"]}
            assert "nomad.broker.total_ready" in gauges
            assert "nomad.plan.queue_depth" in gauges
            assert "nomad.heartbeat.active" in gauges
        finally:
            srv.shutdown()
