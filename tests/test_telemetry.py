"""Telemetry tests: sink aggregation, statsd datagrams, and counters
advancing through a real scheduling cycle (reference shapes: go-metrics
inmem/statsd behavior; EmitStats gauges of eval_broker.go:650-662)."""

import pytest

import socket
import time

from nomad_tpu import mock, telemetry
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import EvalStatusComplete
from nomad_tpu.telemetry.metrics import InMemSink, MetricsRegistry, StatsdSink


from helpers import wait_for  # noqa: E402

pytestmark = pytest.mark.timing_retry  # real timers/sockets: one retry

class TestInMemSink:
    def test_gauge_keeps_last_value(self):
        sink = InMemSink(interval=60.0)
        sink.set_gauge(("a", "b"), 1.0)
        sink.set_gauge(("a", "b"), 5.0)
        snap = sink.snapshot()
        assert snap["Gauges"] == [{"Name": "a.b", "Value": 5.0}]

    def test_samples_aggregate(self):
        sink = InMemSink(interval=60.0)
        for v in (10.0, 20.0, 30.0):
            sink.add_sample(("lat",), v)
        [s] = sink.snapshot()["Samples"]
        assert s["Count"] == 3
        assert s["Sum"] == 60.0
        assert s["Min"] == 10.0 and s["Max"] == 30.0
        assert abs(s["Mean"] - 20.0) < 1e-9

    def test_counters_aggregate(self):
        sink = InMemSink(interval=60.0)
        sink.incr_counter(("hits",), 1)
        sink.incr_counter(("hits",), 1)
        [c] = sink.snapshot()["Counters"]
        assert c["Count"] == 2 and c["Sum"] == 2.0

    def test_interval_rotation_bounded(self):
        sink = InMemSink(interval=1.0, retain=3)
        for i in range(10):
            with sink._lock:
                sink._current(1000.0 + i)  # each stamp its own interval
        assert len(sink._intervals) <= 3

    def test_interval_floored_to_one_second(self):
        # 0 would divide-by-zero inside the swallow-all sink fan-out and
        # silently blank telemetry; sub-second fragments every sample.
        assert InMemSink(interval=0).interval == 1.0
        assert InMemSink(interval=0.001).interval == 1.0


class TestStatsdSink:
    def test_datagrams_cross_the_socket(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(2.0)
        addr = "127.0.0.1:%d" % recv.getsockname()[1]
        sink = StatsdSink(addr)
        sink.set_gauge(("nomad", "broker", "total_ready"), 4)
        sink.incr_counter(("nomad", "rpc", "request"), 1)
        sink.add_sample(("nomad", "fsm", "register_job"), 1.25)
        got = set()
        for _ in range(3):
            got.add(recv.recv(1024).decode())
        assert "nomad.broker.total_ready:4|g" in got
        assert "nomad.rpc.request:1|c" in got
        assert "nomad.fsm.register_job:1.25|ms" in got
        sink.close()
        recv.close()


class TestRegistry:
    def test_measure_records_milliseconds(self):
        reg = MetricsRegistry()
        with reg.measure(("op",)):
            time.sleep(0.01)
        [s] = reg.snapshot()["Samples"]
        assert s["Name"] == "op"
        assert s["Min"] >= 5.0  # ms, not seconds

    def test_broken_sink_never_breaks_caller(self):
        reg = MetricsRegistry()

        class Bad:
            def set_gauge(self, k, v):
                raise RuntimeError("boom")

        reg.add_sink(Bad())
        reg.set_gauge(("g",), 1)  # must not raise
        assert reg.snapshot()["Gauges"][0]["Value"] == 1


class TestSchedulingCycleMetrics:
    def test_counters_advance_through_a_cycle(self):
        """One job register -> schedule -> commit cycle must leave FSM
        apply timers, plan evaluate/apply timers, and broker gauges in the
        global registry (reference: fsm.go:147, plan_apply.go:168,195,
        eval_broker.go:650)."""
        # Fresh in-mem sink with a huge interval: counts cannot rotate away
        # mid-test and earlier tests' noise is discarded.
        telemetry.configure(collection_interval=3600.0)
        before = telemetry.snapshot()

        def sample_count(snap, name):
            for s in snap["Samples"]:
                if s["Name"] == name:
                    return s["Count"]
            return 0

        srv = Server(ServerConfig(num_schedulers=1, dev_mode=True))
        try:
            srv.establish_leadership()
            for _ in range(2):
                srv.node_register(mock.node())
            job = mock.job()
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(lambda: (
                (e := srv.state.eval_by_id(eval_id)) is not None
                and e.Status == EvalStatusComplete))
            srv._emit_stats()
            snap = telemetry.snapshot()
            assert sample_count(snap, "nomad.fsm.register_job") \
                > sample_count(before, "nomad.fsm.register_job")
            assert sample_count(snap, "nomad.fsm.register_node") \
                > sample_count(before, "nomad.fsm.register_node")
            assert sample_count(snap, "nomad.plan.evaluate") \
                > sample_count(before, "nomad.plan.evaluate")
            assert sample_count(snap, "nomad.plan.apply") \
                > sample_count(before, "nomad.plan.apply")
            gauges = {g["Name"] for g in snap["Gauges"]}
            assert "nomad.broker.total_ready" in gauges
            assert "nomad.plan.queue_depth" in gauges
            assert "nomad.heartbeat.active" in gauges
        finally:
            srv.shutdown()
