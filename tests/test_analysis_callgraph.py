"""Call-graph engine tests (analysis/callgraph.py): resolution of bare /
self / method-dispatch calls, transitive reachability from apply roots,
the nondeterminism taxonomy, boundary exclusion, the visibility
restriction on method-name fallback, and suppression plumbing through
run_checks. Synthetic files live outside the package tree, where
apply/_apply_*/restore*-named functions are roots by the external rule.
"""

import os
import textwrap

from nomad_tpu.analysis.callgraph import build_graph
from nomad_tpu.analysis.framework import PKG_ROOT, load_file, run_checks


def _write(tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return str(p)


def _impurities(tmp_path, *sources):
    paths = [_write(tmp_path, f"m{i}.py", src)
             for i, src in enumerate(sources)]
    ctxs = [load_file(p) for p in paths]
    assert all(ctxs)
    return build_graph(ctxs).impurities()


# ------------------------------------------------------------ reachability
def test_direct_impurity_in_apply_root_flags(tmp_path):
    imps = _impurities(tmp_path, """
        import time

        def apply(entry):
            return time.time()
    """)
    assert len(imps) == 1
    imp = imps[0]
    assert imp.category == "wall_clock"
    assert imp.label == "time.time()"
    assert imp.chain == ("apply",)


def test_two_hop_transitive_chain(tmp_path):
    imps = _impurities(tmp_path, """
        import random

        def _stamp():
            return random.random()

        def _decorate(entry):
            entry["n"] = _stamp()

        def apply(entry):
            _decorate(entry)
    """)
    assert len(imps) == 1
    assert imps[0].category == "randomness"
    assert imps[0].chain == ("apply", "_decorate", "_stamp")


def test_self_method_dispatch_resolves(tmp_path):
    imps = _impurities(tmp_path, """
        import uuid

        class FSM:
            def _fresh_id(self):
                return uuid.uuid4()

            def apply(self, entry):
                return self._fresh_id()
    """)
    assert len(imps) == 1
    assert imps[0].label == "uuid.uuid4()"
    assert imps[0].chain == ("FSM.apply", "FSM._fresh_id")


def test_unreachable_impurity_is_not_flagged(tmp_path):
    imps = _impurities(tmp_path, """
        import time

        def observability_tick():
            return time.time()

        def apply(entry):
            return entry
    """)
    assert imps == []


def test_unordered_set_iteration_flags(tmp_path):
    imps = _impurities(tmp_path, """
        def apply(entry):
            out = []
            for k in set(entry):
                out.append(k)
            return out
    """)
    assert [i.category for i in imps] == ["unordered"]


def test_identity_and_io_leaves(tmp_path):
    imps = _impurities(tmp_path, """
        def apply(entry):
            h = hash(entry["ID"])
            with open("/tmp/x") as f:
                return h, f.read()
    """)
    assert {i.category for i in imps} == {"identity", "io"}


# ----------------------------------------------------- visibility / deny
def test_method_fallback_restricted_to_visible_files(tmp_path):
    # m0's apply calls obj.frobnicate() but never imports m1 (and CANNOT:
    # synthetic files are outside the nomad_tpu namespace), so the
    # name-match must not edge into m1's impure method.
    imps = _impurities(tmp_path, """
        def apply(entry, obj):
            obj.frobnicate(entry)
    """, """
        import time

        class Widget:
            def frobnicate(self, entry):
                entry["t"] = time.time()
    """)
    assert imps == []
    # Same shapes in ONE file: the class is visible, the edge resolves.
    same = tmp_path / "same"
    same.mkdir()
    imps = _impurities(same, """
        import time

        class Widget:
            def frobnicate(self, entry):
                entry["t"] = time.time()

        def apply(entry, obj):
            obj.frobnicate(entry)
    """)
    assert len(imps) == 1
    assert imps[0].chain == ("apply", "Widget.frobnicate")


def test_denylisted_container_methods_never_edge(tmp_path):
    imps = _impurities(tmp_path, """
        import time

        class Registry:
            def append(self, entry):
                entry["t"] = time.time()

        def apply(entry, items):
            items.append(entry)
    """)
    assert imps == []


# ----------------------------------------------------------- boundaries
def test_observer_seams_are_traversal_boundaries():
    # Real package files: functions in telemetry/ and the failpoint
    # registry index as boundaries, and rooting a traversal AT one
    # yields nothing — its internals never join the apply closure.
    fp = load_file(os.path.join(PKG_ROOT, "resilience", "failpoints.py"))
    tm = load_file(os.path.join(PKG_ROOT, "telemetry", "metrics.py"))
    assert fp is not None and tm is not None
    graph = build_graph([fp, tm])
    infos = list(graph.functions())
    assert infos and all(i.boundary for i in infos)
    roots = [i.key for i in infos]
    assert graph.impurities(roots=roots) == []


# ------------------------------------------------------------ suppression
def test_allow_comment_suppresses_via_run_checks(tmp_path):
    p = _write(tmp_path, "sup.py", """
        import time

        def apply(entry):
            entry["t"] = time.time()  # lint: allow(apply_pure, local)
    """)
    assert run_checks(paths=[p], checker_ids=["apply_pure"]) == []
    flagged = run_checks(paths=[p], checker_ids=["apply_pure"],
                         include_suppressed=True)
    assert len(flagged) == 1 and flagged[0].suppressed


def test_unsuppressed_surfaces_through_run_checks(tmp_path):
    p = _write(tmp_path, "raw.py", """
        import time

        def apply(entry):
            entry["t"] = time.time()
    """)
    found = run_checks(paths=[p], checker_ids=["apply_pure"])
    assert len(found) == 1
    f = found[0]
    assert f.checker == "apply_pure"
    assert "wall_clock" in f.message and "apply" in f.message
