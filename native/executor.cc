// nomad-executor: the native task supervisor.
//
// The reference runs tasks under a native (Go) executor re-exec'd as a
// separate plugin process (client/driver/executor/ + plugins.go); this is
// the same runtime component in C++. Contract-compatible with the Python
// fallback (nomad_tpu/client/executor.py):
//
//   argv[1] = <spec.json>   {command, args, env, cwd, user?, task_name,
//                            log_dir, max_files, max_file_size_mb,
//                            cgroup?: {cpu_shares, memory_mb}, chroot?}
//   writes  <task>.executor_state.json  {executor_pid, pid, pgid, started_at}
//           <task>.exit_status.json     {exit_code, signal, finished_at}
//   logs    <log_dir>/<task>.stdout.N / .stderr.N, size-rotated
//   signals SIGTERM/SIGINT forwarded to the task's process group
//
// Build: make -C native   (pure standard library + POSIX; no dependencies)

#include <cerrno>
#include <cctype>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <grp.h>
#include <map>
#include <memory>
#include <poll.h>
#include <pwd.h>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

// ---------------------------------------------------------------- tiny JSON
// Parses the executor spec subset: objects, arrays, strings (with escapes),
// numbers, booleans, null.
struct JValue {
  enum Kind { Null, Bool, Num, Str, Arr, Obj } kind = Null;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JValue> arr;
  std::map<std::string, JValue> obj;

  const JValue *get(const std::string &key) const {
    auto it = obj.find(key);
    return it == obj.end() ? nullptr : &it->second;
  }
  std::string get_str(const std::string &key, const std::string &dflt = "") const {
    const JValue *v = get(key);
    return (v && v->kind == Str) ? v->str : dflt;
  }
  long get_int(const std::string &key, long dflt) const {
    const JValue *v = get(key);
    return (v && v->kind == Num) ? (long)v->num : dflt;
  }
};

struct JParser {
  const char *p, *end;
  explicit JParser(const std::string &s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() { while (p < end && isspace((unsigned char)*p)) p++; }
  bool fail(const char *msg) {
    fprintf(stderr, "executor: bad spec json: %s\n", msg);
    exit(2);
  }
  JValue parse() {
    skip_ws();
    if (p >= end) fail("eof");
    char c = *p;
    if (c == '{') return parse_obj();
    if (c == '[') return parse_arr();
    if (c == '"') return parse_str();
    if (c == 't' || c == 'f') return parse_bool();
    if (c == 'n') { p += 4; return JValue{}; }
    return parse_num();
  }
  JValue parse_obj() {
    JValue v; v.kind = JValue::Obj; p++;  // '{'
    skip_ws();
    if (p < end && *p == '}') { p++; return v; }
    while (p < end) {
      skip_ws();
      JValue key = parse_str();
      skip_ws();
      if (p >= end || *p != ':') fail("expected ':'");
      p++;
      v.obj[key.str] = parse();
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == '}') { p++; break; }
      fail("expected ',' or '}'");
    }
    return v;
  }
  JValue parse_arr() {
    JValue v; v.kind = JValue::Arr; p++;  // '['
    skip_ws();
    if (p < end && *p == ']') { p++; return v; }
    while (p < end) {
      v.arr.push_back(parse());
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == ']') { p++; break; }
      fail("expected ',' or ']'");
    }
    return v;
  }
  JValue parse_str() {
    if (*p != '"') fail("expected string");
    p++;
    JValue v; v.kind = JValue::Str;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        p++;
        switch (*p) {
          case 'n': v.str += '\n'; break;
          case 't': v.str += '\t'; break;
          case 'r': v.str += '\r'; break;
          case 'b': v.str += '\b'; break;
          case 'f': v.str += '\f'; break;
          case '/': v.str += '/'; break;
          case '\\': v.str += '\\'; break;
          case '"': v.str += '"'; break;
          case 'u': {
            if (p + 4 >= end) fail("bad \\u");
            unsigned cp = (unsigned)strtoul(std::string(p + 1, p + 5).c_str(),
                                            nullptr, 16);
            p += 4;
            // UTF-8 encode (surrogate pairs for env values are not expected
            // from the Python json emitter's ascii output for BMP chars;
            // handle pairs anyway).
            if (cp >= 0xD800 && cp <= 0xDBFF && p + 6 < end && p[1] == '\\'
                && p[2] == 'u') {
              unsigned lo = (unsigned)strtoul(std::string(p + 3, p + 7).c_str(),
                                              nullptr, 16);
              p += 6;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            if (cp < 0x80) v.str += (char)cp;
            else if (cp < 0x800) {
              v.str += (char)(0xC0 | (cp >> 6));
              v.str += (char)(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              v.str += (char)(0xE0 | (cp >> 12));
              v.str += (char)(0x80 | ((cp >> 6) & 0x3F));
              v.str += (char)(0x80 | (cp & 0x3F));
            } else {
              v.str += (char)(0xF0 | (cp >> 18));
              v.str += (char)(0x80 | ((cp >> 12) & 0x3F));
              v.str += (char)(0x80 | ((cp >> 6) & 0x3F));
              v.str += (char)(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: v.str += *p;
        }
      } else {
        v.str += *p;
      }
      p++;
    }
    if (p >= end) fail("unterminated string");
    p++;  // closing quote
    return v;
  }
  JValue parse_bool() {
    JValue v; v.kind = JValue::Bool;
    if (*p == 't') { v.b = true; p += 4; } else { v.b = false; p += 5; }
    return v;
  }
  JValue parse_num() {
    JValue v; v.kind = JValue::Num;
    char *np = nullptr;
    v.num = strtod(p, &np);
    if (np == p) fail("bad number");
    p = np;
    return v;
  }
};

static std::string json_escape(const std::string &s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else out += c;
    }
  }
  return out;
}

// ------------------------------------------------------------- log rotator
// Mirrors nomad_tpu/client/logs.py FileRotator: <base>.N files, rotate at
// max_size bytes, prune the oldest beyond max_files.
class Rotator {
 public:
  Rotator(std::string dir, std::string base, int max_files, long max_size)
      : dir_(std::move(dir)), base_(std::move(base)),
        max_files_(max_files < 1 ? 1 : max_files),
        max_size_(max_size < 1 ? 1 : max_size) {
    index_ = find_latest_index();
    open_current();
  }
  ~Rotator() { if (fd_ >= 0) close(fd_); }

  void write(const char *buf, ssize_t n) {
    if (fd_ < 0) return;
    if (written_ + n > max_size_) rotate();
    ssize_t off = 0;
    while (off < n) {
      ssize_t w = ::write(fd_, buf + off, (size_t)(n - off));
      if (w <= 0) return;
      off += w;
    }
    written_ += n;
  }

 private:
  std::string file(int index) const {
    return dir_ + "/" + base_ + "." + std::to_string(index);
  }
  int find_latest_index() const {
    // Cheap probe: walk indexes upward until a file is missing.
    int best = 0;
    for (int i = 0; i < 100000; i++) {
      struct stat st;
      if (stat(file(i).c_str(), &st) == 0) best = i; else if (i > best) break;
    }
    return best;
  }
  void open_current() {
    mkdir(dir_.c_str(), 0755);
    // O_CLOEXEC: the task must not inherit writable fds to its own logs.
    fd_ = open(file(index_).c_str(),
               O_WRONLY | O_CREAT | O_APPEND | O_CLOEXEC, 0644);
    written_ = 0;
    if (fd_ >= 0) {
      struct stat st;
      if (fstat(fd_, &st) == 0) written_ = st.st_size;
    }
  }
  void rotate() {
    if (fd_ >= 0) close(fd_);
    index_++;
    int prune = index_ - max_files_;
    if (prune >= 0) unlink(file(prune).c_str());
    open_current();
  }

  std::string dir_, base_;
  int max_files_;
  long max_size_;
  int index_ = 0;
  int fd_ = -1;
  long written_ = 0;
};

// ---------------------------------------------------------------- cgroups
static std::string cgroup_path(const std::string &task) {
  return "/sys/fs/cgroup/nomad_tpu_" + task + "_" + std::to_string(getpid());
}

static void write_file(const std::string &path, const std::string &value) {
  int fd = open(path.c_str(), O_WRONLY);
  if (fd < 0) return;
  ssize_t unused = ::write(fd, value.data(), value.size());
  (void)unused;
  close(fd);
}

static void apply_cgroup(const JValue *cfg, const std::string &task, pid_t pid) {
  if (!cfg || cfg->kind != JValue::Obj) return;
  std::string path = cgroup_path(task);
  if (mkdir(path.c_str(), 0755) != 0 && errno != EEXIST) return;
  long mem_mb = cfg->get_int("memory_mb", 0);
  if (mem_mb > 0)
    write_file(path + "/memory.max", std::to_string(mem_mb * 1024 * 1024));
  long cpu = cfg->get_int("cpu_shares", 0);
  if (cpu > 0) {
    if (cpu < 1) cpu = 1;
    if (cpu > 10000) cpu = 10000;
    write_file(path + "/cpu.weight", std::to_string(cpu));
  }
  write_file(path + "/cgroup.procs", std::to_string(pid));
}

static void cleanup_cgroup(const std::string &task) {
  rmdir(cgroup_path(task).c_str());
}

// ------------------------------------------------------------------- main
static pid_t g_child_pgid = 0;
static void forward_signal(int signum) {
  if (g_child_pgid > 0) kill(-g_child_pgid, signum);
}

static void write_atomic(const std::string &path, const std::string &content) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return;
  ssize_t unused = ::write(fd, content.data(), content.size());
  (void)unused;
  close(fd);
  rename(tmp.c_str(), path.c_str());
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: nomad-executor <spec.json>\n");
    return 2;
  }
  // Read the spec.
  FILE *f = fopen(argv[1], "rb");
  if (!f) { perror("executor: open spec"); return 2; }
  std::string text;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof buf, f)) > 0) text.append(buf, n);
  fclose(f);
  JValue spec = JParser(text).parse();

  std::string task = spec.get_str("task_name", "task");
  std::string base_dir = argv[1];
  size_t slash = base_dir.rfind('/');
  base_dir = (slash == std::string::npos) ? "." : base_dir.substr(0, slash);
  std::string state_path = base_dir + "/" + task + ".executor_state.json";
  std::string exit_path = base_dir + "/" + task + ".exit_status.json";

  std::string log_dir = spec.get_str("log_dir", base_dir);
  long max_files = spec.get_int("max_files", 10);
  long max_size = spec.get_int("max_file_size_mb", 10) * 1024 * 1024;
  Rotator out(log_dir, task + ".stdout", (int)max_files, max_size);
  Rotator err(log_dir, task + ".stderr", (int)max_files, max_size);

  int out_pipe[2], err_pipe[2];
  if (pipe(out_pipe) != 0 || pipe(err_pipe) != 0) {
    perror("executor: pipe");
    return 2;
  }

  pid_t pid = fork();
  if (pid < 0) { perror("executor: fork"); return 2; }
  if (pid == 0) {
    // Child: own session/pgid, optional chroot + setuid, exec the task.
    setsid();
    dup2(out_pipe[1], 1);
    dup2(err_pipe[1], 2);
    close(out_pipe[0]); close(out_pipe[1]);
    close(err_pipe[0]); close(err_pipe[1]);

    // Match the Python supervisor's ordering (CPython's child does
    // chdir(cwd) before preexec_fn): host-path cwd first, then chroot,
    // leaving a chrooted task at "/".
    std::string cwd = spec.get_str("cwd");
    if (!cwd.empty() && chdir(cwd.c_str()) != 0) {
      perror("executor: chdir");
      _exit(125);
    }
    std::string root = spec.get_str("chroot");
    if (!root.empty()) {
      if (chroot(root.c_str()) != 0 || chdir("/") != 0) {
        perror("executor: chroot");
        _exit(125);
      }
    }
    std::string user = spec.get_str("user");
    if (!user.empty()) {
      struct passwd *pw = getpwnam(user.c_str());
      if (!pw || setgid(pw->pw_gid) != 0 || setuid(pw->pw_uid) != 0) {
        fprintf(stderr, "executor: cannot become user %s\n", user.c_str());
        _exit(125);
      }
    }
    // argv
    std::vector<std::string> args_s{spec.get_str("command")};
    const JValue *jargs = spec.get("args");
    if (jargs && jargs->kind == JValue::Arr)
      for (const auto &a : jargs->arr) args_s.push_back(a.str);
    std::vector<char *> args_c;
    for (auto &s : args_s) args_c.push_back(const_cast<char *>(s.c_str()));
    args_c.push_back(nullptr);

    // env
    std::vector<std::string> env_s;
    const JValue *jenv = spec.get("env");
    if (jenv && jenv->kind == JValue::Obj)
      for (const auto &kv : jenv->obj)
        env_s.push_back(kv.first + "=" + kv.second.str);
    std::vector<char *> env_c;
    for (auto &s : env_s) env_c.push_back(const_cast<char *>(s.c_str()));
    env_c.push_back(nullptr);

    // execvpe: PATH-resolve bare command names exactly like the Python
    // supervisor's subprocess.Popen does.
    execvpe(args_c[0], args_c.data(),
            (jenv && jenv->kind == JValue::Obj) ? env_c.data() : environ);
    fprintf(stderr, "executor: exec %s: %s\n", args_c[0], strerror(errno));
    _exit(127);
  }

  // Parent (the supervisor).
  close(out_pipe[1]);
  close(err_pipe[1]);
  apply_cgroup(spec.get("cgroup"), task, pid);

  g_child_pgid = pid;
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = forward_signal;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  {
    char state[256];
    snprintf(state, sizeof state,
             "{\"executor_pid\": %d, \"pid\": %d, \"pgid\": %d, "
             "\"started_at\": %ld, \"native\": true}",
             getpid(), pid, pid, (long)time(nullptr));
    write_atomic(state_path, state);
  }

  // Pump both pipes until EOF — but report the CHILD's exit even while a
  // grandchild keeps the pipes open (matching the Python supervisor, which
  // reports on proc.wait() and gives the pumps a bounded grace period).
  struct pollfd fds[2] = {{out_pipe[0], POLLIN, 0}, {err_pipe[0], POLLIN, 0}};
  Rotator *rots[2] = {&out, &err};
  int open_fds = 2;
  char io[65536];
  int status = 0;
  bool reaped = false;
  time_t drain_deadline = 0;
  while (open_fds > 0) {
    if (!reaped) {
      pid_t r = waitpid(pid, &status, WNOHANG);
      if (r == pid) {
        reaped = true;
        drain_deadline = time(nullptr) + 5;  // grace for buffered output
      }
    } else if (time(nullptr) >= drain_deadline) {
      break;
    }
    int rc = poll(fds, 2, 200);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (rc == 0) continue;
    for (int i = 0; i < 2; i++) {
      if (fds[i].fd < 0) continue;
      if (fds[i].revents & (POLLIN | POLLHUP)) {
        ssize_t r = read(fds[i].fd, io, sizeof io);
        if (r > 0) {
          rots[i]->write(io, r);
        } else if (r == 0 || (r < 0 && errno != EINTR)) {
          close(fds[i].fd);
          fds[i].fd = -1;
          open_fds--;
        }
      } else if (fds[i].revents & (POLLERR | POLLNVAL)) {
        close(fds[i].fd);
        fds[i].fd = -1;
        open_fds--;
      }
    }
  }

  if (!reaped)
    while (waitpid(pid, &status, 0) < 0 && errno == EINTR) {}
  int exit_code = 0, sig = 0;
  if (WIFEXITED(status)) exit_code = WEXITSTATUS(status);
  else if (WIFSIGNALED(status)) sig = WTERMSIG(status);

  char result[192];
  snprintf(result, sizeof result,
           "{\"exit_code\": %d, \"signal\": %d, \"finished_at\": %ld}",
           exit_code, sig, (long)time(nullptr));
  write_atomic(exit_path, result);
  cleanup_cgroup(task);
  (void)json_escape;  // reserved for richer state payloads
  return 0;
}
