// Native raft segment-log backend (nomad_tpu/raft/log.py format v2).
//
// The reference persists its raft log through raft-boltdb (a native Go
// B-tree store); this is the rebuild's native equivalent for the same
// role: CRC-framed append-only segment with fsync'd group appends,
// mmap-scanned validated replay, and atomic rewrite for compaction.
// The file format is SHARED with the Python FileLogStore ("NTL2" magic,
// [u32 len][u32 crc32(payload)][payload] records, little-endian), so a
// node can move between the native and Python backends freely.
//
// Exposed as a C API consumed via ctypes (nomad_tpu/raft/native_log.py).
// Build: make -C native  ->  native/bin/liblogstore.so

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <zlib.h>

namespace {

constexpr char kMagic[4] = {'N', 'T', 'L', '2'};

struct Store {
  std::string path;
  int fd = -1;
};

void set_err(char* err, int errlen, const std::string& msg) {
  if (err != nullptr && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;  // x86/arm little-endian, same as Python struct "<I"
}

void wr32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }

bool full_write(int fd, const uint8_t* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = write(fd, buf + off, n - off);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    off += static_cast<size_t>(w);
  }
  return true;
}

}  // namespace

extern "C" {

// Open (creating if missing, writing the magic header). Returns a handle
// or null with `err` filled.
void* lgs_open(const char* path, char* err, int errlen) {
  auto* s = new Store();
  s->path = path;
  s->fd = open(path, O_RDWR | O_CREAT, 0644);
  if (s->fd < 0) {
    set_err(err, errlen, std::string("open: ") + strerror(errno));
    delete s;
    return nullptr;
  }
  struct stat st;
  if (fstat(s->fd, &st) == 0 && st.st_size == 0) {
    if (!full_write(s->fd, reinterpret_cast<const uint8_t*>(kMagic), 4) ||
        fdatasync(s->fd) != 0) {
      set_err(err, errlen, "magic write failed");
      close(s->fd);
      delete s;
      return nullptr;
    }
  }
  if (lseek(s->fd, 0, SEEK_END) < 0) {
    set_err(err, errlen, "seek failed");
    close(s->fd);
    delete s;
    return nullptr;
  }
  return s;
}

// Scan + CRC-validate the whole file (mmap'd); truncates a torn or corrupt
// tail ON DISK. Returns a malloc'd buffer of concatenated
// [u32 len][payload] frames (CRC verified and stripped) with *out_n set,
// or null on error. A valid empty log returns a non-null empty buffer.
uint8_t* lgs_replay(void* handle, long* out_n, char* err, int errlen) {
  auto* s = static_cast<Store*>(handle);
  *out_n = 0;
  struct stat st;
  if (fstat(s->fd, &st) != 0) {
    set_err(err, errlen, "fstat failed");
    return nullptr;
  }
  size_t n = static_cast<size_t>(st.st_size);
  auto* out = static_cast<uint8_t*>(malloc(n > 0 ? n : 1));
  if (out == nullptr) {
    set_err(err, errlen, "oom");
    return nullptr;
  }
  if (n <= 4) {  // empty or header-only
    if (n != 0 && n < 4) {
      // Torn header: rewrite it.
      if (ftruncate(s->fd, 0) == 0) {
        (void)!full_write(s->fd, reinterpret_cast<const uint8_t*>(kMagic),
                          4);
        (void)fdatasync(s->fd);
      }
    }
    lseek(s->fd, 0, SEEK_END);
    return out;
  }
  void* mem = mmap(nullptr, n, PROT_READ, MAP_PRIVATE, s->fd, 0);
  if (mem == MAP_FAILED) {
    set_err(err, errlen, "mmap failed");
    free(out);
    return nullptr;
  }
  const auto* raw = static_cast<const uint8_t*>(mem);
  size_t off = 4;  // past magic (a legacy headerless file is handled by
                   // the Python side before choosing this backend)
  if (memcmp(raw, kMagic, 4) != 0) {
    munmap(mem, n);
    free(out);
    set_err(err, errlen, "not an NTL2 segment");
    return nullptr;
  }
  size_t w = 0;
  while (off + 8 <= n) {
    uint32_t len = rd32(raw + off);
    uint32_t crc = rd32(raw + off + 4);
    if (off + 8 + len > n) break;  // torn tail
    const uint8_t* payload = raw + off + 8;
    if (crc32(0L, payload, len) != crc) break;  // corrupt record
    wr32(out + w, len);
    memcpy(out + w + 4, payload, len);
    w += 4 + len;
    off += 8 + len;
  }
  munmap(mem, n);
  if (off < n) {
    // Drop the invalid tail on disk so appends land after valid data.
    if (ftruncate(s->fd, static_cast<off_t>(off)) != 0) {
      set_err(err, errlen, "truncate of corrupt tail failed");
      free(out);
      return nullptr;
    }
  }
  lseek(s->fd, 0, SEEK_END);
  *out_n = static_cast<long>(w);
  return out;
}

void lgs_free(uint8_t* p) { free(p); }

// Append a batch: `frames` is concatenated [u32 len][payload]; each
// payload is CRC-framed and the whole batch lands with one fdatasync.
int lgs_append(void* handle, const uint8_t* frames, long n) {
  auto* s = static_cast<Store*>(handle);
  if (s->fd < 0) return -5;  // poisoned by a failed rewrite reopen
  auto* buf = static_cast<uint8_t*>(malloc(static_cast<size_t>(n) * 2 + 8));
  if (buf == nullptr) return -1;
  size_t w = 0;
  long off = 0;
  while (off + 4 <= n) {
    uint32_t len = rd32(frames + off);
    if (off + 4 + static_cast<long>(len) > n) {
      free(buf);
      return -2;  // malformed input batch
    }
    const uint8_t* payload = frames + off + 4;
    wr32(buf + w, len);
    wr32(buf + w + 4, crc32(0L, payload, len));
    memcpy(buf + w + 8, payload, len);
    w += 8 + len;
    off += 4 + len;
  }
  int rc = 0;
  if (!full_write(s->fd, buf, w) || fdatasync(s->fd) != 0) rc = -3;
  free(buf);
  return rc;
}

// Atomic rewrite (compaction/truncation): same batch input as lgs_append,
// written to <path>.tmp then renamed over the segment.
int lgs_rewrite(void* handle, const uint8_t* frames, long n) {
  auto* s = static_cast<Store*>(handle);
  std::string tmp = s->path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  bool ok = full_write(fd, reinterpret_cast<const uint8_t*>(kMagic), 4);
  long off = 0;
  while (ok && off + 4 <= n) {
    uint32_t len = rd32(frames + off);
    if (off + 4 + static_cast<long>(len) > n) {
      ok = false;
      break;
    }
    const uint8_t* payload = frames + off + 4;
    uint8_t hdr[8];
    wr32(hdr, len);
    wr32(hdr + 4, crc32(0L, payload, len));
    ok = full_write(fd, hdr, 8) && full_write(fd, payload, len);
    off += 4 + len;
  }
  ok = ok && fdatasync(fd) == 0;
  close(fd);
  if (!ok) {
    unlink(tmp.c_str());
    return -2;
  }
  if (rename(tmp.c_str(), s->path.c_str()) != 0) return -3;
  // Swap the live fd to the new segment. The old fd points at the
  // renamed-over (unlinked) inode either way: close it FIRST, and on
  // reopen failure poison the handle — appending to the unlinked inode
  // would acknowledge entries that vanish on restart.
  close(s->fd);
  s->fd = open(s->path.c_str(), O_RDWR, 0644);
  if (s->fd < 0) return -4;
  lseek(s->fd, 0, SEEK_END);
  return 0;
}

void lgs_close(void* handle) {
  auto* s = static_cast<Store*>(handle);
  if (s->fd >= 0) close(s->fd);
  delete s;
}

}  // extern "C"
